"""graftlint analyzer self-tests: every rule has one known-bad fixture
(the lint must flag it) and one known-good twin (the lint must stay
silent), plus the runtime lock tracker's inversion tests — including
the PR 6 ``MasterClient`` bug-class regression.
"""

import json
import os
import textwrap
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis import jaxpr_audit as ja
from paddle_tpu.analysis.ast_lints import (lint_layer_matrix, run_pass1)
from paddle_tpu.analysis.baseline import (apply_baseline, load_baseline)
from paddle_tpu.analysis.bench_schema import check_bench_file
from paddle_tpu.analysis.findings import Finding
from paddle_tpu.analysis.lockorder import run_pass3
from paddle_tpu.testing import lockcheck


# ---------------------------------------------------------------- helpers
def _lint_snippet(tmp_path, source, rel="paddle_tpu/serving/mod.py"):
    """Write one fixture module into a fake repo root and lint it."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, suppressed = run_pass1(str(tmp_path), paths=[str(path)])
    return findings, suppressed


def _rules(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------ PT101 fixtures
BAD_CLOSURE = """
    import jax
    import jax.numpy as jnp

    def make_step():
        params = jnp.ones((4, 4))

        def step(x):
            return x @ params  # captured device array

        return jax.jit(step)
"""

GOOD_CLOSURE = """
    import jax
    import jax.numpy as jnp

    def make_step():
        def step(params, x):
            return x @ params

        return jax.jit(step)
"""


def test_pt101_flags_closure_captured_array(tmp_path):
    findings, _ = _lint_snippet(tmp_path, BAD_CLOSURE)
    assert "PT101" in _rules(findings)
    assert "params" in [f for f in findings
                        if f.rule == "PT101"][0].message


def test_pt101_silent_on_traced_args(tmp_path):
    findings, _ = _lint_snippet(tmp_path, GOOD_CLOSURE)
    assert "PT101" not in _rules(findings)


def test_pt101_name_heuristic_catches_feed_capture(tmp_path):
    # the exact shape of the cmd_checkgrad violation this PR fixed
    findings, _ = _lint_snippet(tmp_path, """
        import jax

        def check(feeder, data, net):
            feed = feeder(data) if feeder is not None else data

            @jax.jit
            def loss_fn(params):
                return net.apply(params, feed)

            return loss_fn
    """)
    assert "PT101" in _rules(findings)


def test_pt101_catches_parameter_capture(tmp_path):
    """Review regression: capturing an enclosing function's PARAMETER
    (not a local assignment) is the same embedded-constant deopt and
    must flag; passing it as a traced arg stays silent."""
    findings, _ = _lint_snippet(tmp_path, """
        import jax

        def check(net, feed):
            @jax.jit
            def loss_fn(params):
                return net.apply(params, feed)

            return loss_fn
    """)
    assert "PT101" in _rules(findings)
    findings, _ = _lint_snippet(tmp_path, """
        import jax

        def check(net, feed):
            @jax.jit
            def loss_fn(params, feed):
                return net.apply(params, feed)

            return loss_fn
    """)
    assert "PT101" not in _rules(findings)


# ------------------------------------------------------ PT102 fixtures
def test_pt102_flags_mask_bf16_cast(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def cast(feed):
            return feed["mask"].astype(jnp.bfloat16)
    """)
    assert "PT102" in _rules(findings)


def test_pt102_silent_on_value_cast_and_f32_mask(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def cast(feed):
            v = feed["value"].astype(jnp.bfloat16)   # values may cast
            m = feed["mask"].astype(jnp.float32)     # masks stay f32
            return v, m
    """)
    assert "PT102" not in _rules(findings)


# ------------------------------------------------------ PT103 fixtures
def test_pt103_flags_pad_in_optim(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def _pack(flat, n, chunk):
            flat = jnp.pad(flat, (0, n * chunk - flat.shape[0]))
            return flat.reshape(n, chunk)
    """, rel="paddle_tpu/optim/packer.py")
    assert "PT103" in _rules(findings)


def test_pt103_flags_marked_function_outside_optim(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        # graftlint: bit-exact
        def pack(flat, pad):
            return jnp.pad(flat, (0, pad))
    """, rel="paddle_tpu/parallel/util.py")
    assert "PT103" in _rules(findings)


def test_pt103_silent_on_concatenate_pack_and_layer_pad(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def _pack(flat, n, chunk):
            pad = n * chunk - flat.shape[0]
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad,), flat.dtype)])
            return flat.reshape(n, chunk)
    """, rel="paddle_tpu/optim/packer.py")
    assert "PT103" not in _rules(findings)
    # jnp.pad with padding SEMANTICS (a pad layer) is legal outside
    findings, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def pad_layer(x, ph, pw):
            return jnp.pad(x, ((0, 0), ph, pw, (0, 0)))
    """, rel="paddle_tpu/layers/padding.py")
    assert "PT103" not in _rules(findings)


# ------------------------------------------------------ PT104 fixtures
def test_pt104_flags_unguarded_persistent_jit(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax

        class Predictor:
            def __init__(self, fwd):
                self._infer = jax.jit(fwd)
    """)
    assert "PT104" in _rules(findings)


def test_pt104_satisfied_by_guard_or_policy_note(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax
        from paddle_tpu.data.prefetch import RecompileGuard

        class Predictor:
            def __init__(self, fwd, enc):
                self._infer = jax.jit(fwd)
                self.guard = RecompileGuard(self._infer)
                # graftlint: jit-cache: LRU-bounded elsewhere
                self._encode = jax.jit(enc)
    """)
    assert "PT104" not in _rules(findings)


def test_pt104_one_shot_jit_exempt_and_scope_limited(tmp_path):
    findings, _ = _lint_snippet(tmp_path, """
        import jax

        def once(fwd, x):
            return jax.jit(fwd)(x)   # immediately invoked: one-shot
    """)
    assert "PT104" not in _rules(findings)
    # outside the hot-path module scope the rule does not apply
    findings, _ = _lint_snippet(tmp_path, """
        import jax

        class Builder:
            def __init__(self, fn):
                self.jitted = jax.jit(fn)
    """, rel="paddle_tpu/parallel/helper.py")
    assert "PT104" not in _rules(findings)


def test_pt104_sees_through_builder_return_chain(tmp_path):
    # `return jax.jit(...)` inside _build_x, assigned via
    # self._step = self._build_x(), guarded under the attr name —
    # the trainer.py shape
    findings, _ = _lint_snippet(tmp_path, """
        import jax
        from paddle_tpu.data.prefetch import RecompileGuard

        class T:
            def __init__(self, fn):
                self._step = self._build_step(fn)
                self.guard = RecompileGuard(self._step)

            def _build_step(self, fn):
                return jax.jit(fn)
    """)
    assert "PT104" not in _rules(findings)


# ------------------------------------------------------ PT105 fixtures
def test_pt105_flags_broad_pkill_in_shell_and_python(tmp_path):
    sh = tmp_path / "tools" / "watch.sh"
    sh.parent.mkdir(parents=True, exist_ok=True)
    sh.write_text("#!/bin/bash\npkill -f python\n")
    findings, _ = run_pass1(str(tmp_path), paths=[str(sh)])
    assert "PT105" in _rules(findings)
    findings, _ = _lint_snippet(tmp_path, """
        import os

        def stop():
            os.system("pkill -f jax")
    """, rel="tools/stop.py")
    assert "PT105" in _rules(findings)


def test_pt105_silent_on_narrow_pattern_and_docstrings(tmp_path):
    sh = tmp_path / "tools" / "watch.sh"
    sh.parent.mkdir(parents=True, exist_ok=True)
    sh.write_text("#!/bin/bash\n"
                  "pkill -f 'tools/tpu_evidence.py --round r99'\n")
    findings, _ = run_pass1(str(tmp_path), paths=[str(sh)])
    assert "PT105" not in _rules(findings)
    # a docstring MENTIONING pkill -f python is not a kill command
    findings, _ = _lint_snippet(tmp_path, '''
        def helper():
            """Never run `pkill -f python` on this host."""
            return 1
    ''', rel="tools/doc.py")
    assert "PT105" not in _rules(findings)


# ------------------------------------------------------ PT106 fixtures
def _matrix_tree(tmp_path, covered):
    (tmp_path / "paddle_tpu" / "layers").mkdir(parents=True,
                                               exist_ok=True)
    (tmp_path / "tests").mkdir(exist_ok=True)
    (tmp_path / "paddle_tpu" / "layers" / "x.py").write_text(
        textwrap.dedent("""
            from paddle_tpu.core.registry import register_layer

            @register_layer("zzz_test_layer")
            class Z:
                pass
        """))
    rows = '"zzz_test_layer": None' if covered else ""
    (tmp_path / "tests" / "test_layer_grad_matrix.py").write_text(
        f"GRAD_CASES = {{{rows}}}\nFWD_CASES = {{}}\n"
        "COVERED_ELSEWHERE = {}\n")


def test_pt106_flags_missing_matrix_row(tmp_path):
    _matrix_tree(tmp_path, covered=False)
    findings = lint_layer_matrix(str(tmp_path))
    assert [f.rule for f in findings] == ["PT106"]
    assert "zzz_test_layer" in findings[0].message


def test_pt106_silent_when_covered(tmp_path):
    _matrix_tree(tmp_path, covered=True)
    assert lint_layer_matrix(str(tmp_path)) == []


# ------------------------------------------------- inline suppression
def test_inline_suppression_counts_and_silences(tmp_path):
    findings, suppressed = _lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        def make_step():
            params = jnp.ones((4, 4))

            # graftlint: disable=jit-closure-capture
            def step(x):
                return x @ params

            return jax.jit(step)
    """)
    assert "PT101" not in _rules(findings)
    assert suppressed == 1


# ------------------------------------------------------ PT2xx audits
def test_pt201_flags_embedded_constant():
    big = jnp.ones((200, 200), jnp.float32)  # 160 KB > CONST_LIMIT

    def bad(x):
        return x @ big

    closed = jax.make_jaxpr(bad)(jnp.ones((2, 200)))
    findings = ja._const_findings(closed, "bad", "x.py")
    assert [f.rule for f in findings] == ["PT201"]

    def good(w, x):
        return x @ w

    closed = jax.make_jaxpr(good)(big, jnp.ones((2, 200)))
    assert ja._const_findings(closed, "good", "x.py") == []


def test_pt203_flags_mask_convert_to_bf16():
    ex = ({"v": jnp.ones((2, 3)), "mask": jnp.ones((2, 3))},)

    def bad(feed):
        # deliberate bad fixture for the jaxpr-level check below
        m16 = feed["mask"].astype(jnp.bfloat16)  # graftlint: disable=PT102
        return (feed["v"].astype(jnp.bfloat16) * m16).sum()

    closed = jax.make_jaxpr(bad)(*ex)
    findings = ja._mask_findings(closed, ja._mask_positions(ex),
                                 "bad", "x.py")
    assert [f.rule for f in findings] == ["PT203"]

    def good(feed):
        return (feed["v"].astype(jnp.bfloat16).astype(jnp.float32)
                * feed["mask"]).sum()

    closed = jax.make_jaxpr(good)(*ex)
    assert ja._mask_findings(closed, ja._mask_positions(ex),
                             "good", "x.py") == []


def test_pt203_taint_flows_through_reshape():
    ex = ({"mask": jnp.ones((2, 3))},)

    def bad(feed):
        # graftlint: disable=mask-bf16-cast — deliberate bad fixture
        return feed["mask"].reshape(-1).astype(jnp.bfloat16).sum()

    closed = jax.make_jaxpr(bad)(*ex)
    findings = ja._mask_findings(closed, ja._mask_positions(ex),
                                 "bad", "x.py")
    assert [f.rule for f in findings] == ["PT203"]


def test_pt202_donation_detects_missing_alias():
    x = jnp.ones((8,), jnp.float32)
    good = jax.jit(lambda a: a * 2, donate_argnums=(0,))
    findings, stats = ja._donation_findings(good, (x,), (0,), "g",
                                            "x.py")
    assert findings == [] and stats["aliased"] == 1
    # donation NOT declared but buffer aliasable: audit of an
    # undonated jit reports the gap when asked to treat arg 0 donated
    bad = jax.jit(lambda a: a * 2)
    findings, stats = ja._donation_findings(bad, (x,), (0,), "b",
                                            "x.py")
    assert [f.rule for f in findings] == ["PT202"]
    assert stats["aliased"] == 0 and stats["aliasable"] == 1


# ------------------------------------------------------ PT3xx static
BAD_LOCK_MODULE = """
    import threading

    class Wire:
        def __init__(self):
            self._sock_lock = threading.Lock()
            self._resp_lock = threading.Lock()

        def call(self):
            with self._sock_lock:
                with self._resp_lock:
                    pass

        def heartbeat(self):
            with self._resp_lock:
                with self._sock_lock:
                    pass
"""

GOOD_LOCK_MODULE = """
    import threading

    class Wire:
        def __init__(self):
            self._sock_lock = threading.Lock()
            self._resp_lock = threading.Lock()

        def call(self):
            with self._sock_lock:
                with self._resp_lock:
                    pass

        def heartbeat(self):
            with self._sock_lock:
                with self._resp_lock:
                    pass
"""


def _lock_check(tmp_path, source, name="wire.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    findings, checker = run_pass3(str(tmp_path), modules=[name])
    return findings, checker


def test_pt301_flags_static_lock_inversion(tmp_path):
    findings, _ = _lock_check(tmp_path, BAD_LOCK_MODULE)
    assert "PT301" in [f.rule for f in findings]


def test_pt301_silent_on_consistent_order(tmp_path):
    findings, checker = _lock_check(tmp_path, GOOD_LOCK_MODULE)
    assert findings == []
    assert len(checker.edges) == 1  # sock -> resp recorded once


def test_pt302_flags_self_deadlock_through_call_chain(tmp_path):
    findings, _ = _lock_check(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """)
    assert "PT302" in [f.rule for f in findings]


def test_pt301_sees_locks_nested_under_control_flow(tmp_path):
    """Review regression: a `with self._lock:` under try/for/if (i.e.
    virtually every worker-loop lock site) must be recorded with its
    held context — the first cut silently skipped them."""
    findings, checker = _lock_check(tmp_path, """
        import threading

        class Wire:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def call(self):
                for _attempt in range(3):
                    try:
                        with self._a:
                            if _attempt:
                                with self._b:
                                    pass
                    except OSError:
                        pass

            def teardown(self):
                while True:
                    with self._b:
                        with self._a:
                            return
    """)
    assert "PT301" in [f.rule for f in findings]


def test_pass3_records_worker_loop_acquisitions():
    """The real modules' loop/try-nested lock sites are in the graph:
    MasterClient's per-exchange lock (the PR 6 site, under for+try —
    since r15 the retry cycle lives in ``_call_retrying``, with
    ``call`` a thin tracing wrapper above it) and the batcher worker's
    except-path lock."""
    from paddle_tpu.analysis.lockorder import LockOrderChecker
    ck = LockOrderChecker(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ck.run()
    call = ck.methods[
        "paddle_tpu.dist.master.MasterClient._call_retrying"]
    assert any(i == "paddle_tpu.dist.master.MasterClient._lock"
               for _h, i, _l in call.acquires)
    work = ck.methods["paddle_tpu.serving.batcher.ServingEngine._work"]
    assert any(i == "paddle_tpu.serving.batcher.ServingEngine._lock"
               for _h, i, _l in work.acquires)


def test_pt301_module_level_function_call_edges(tmp_path):
    """Review regression: callers that are MODULE-LEVEL functions (not
    methods) must still resolve bare-name callees in the same module —
    the first resolver mis-split dotted module names and dropped these
    edges entirely."""
    # a dotted fake-package path mirrors the real modules' depth
    findings, checker = _lock_check(tmp_path, """
        import threading

        class Holder:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

        H = None

        def path_one(h):
            with h._a_proxy:
                pass

        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    helper_b(self)

            def rev(self):
                with self._b:
                    helper_a(self)

        def helper_a(obj):
            obj._a.acquire()
            obj._a.release()

        def helper_b(obj):
            obj._b.acquire()
            obj._b.release()
    """, name="pkg_mod.py")
    # helper_a/_b are module functions; their .acquire on a passed
    # object is unresolvable by design — but the METHOD->module-fn
    # call edge must resolve, which requires the module qual to be
    # computed right. Assert resolution works at all:
    assert checker._resolve_callee(
        "helper_b", "pkg_mod.A.fwd") == "pkg_mod.helper_b"
    # and for a module-level caller in the same module:
    assert checker._resolve_callee(
        "helper_a", "pkg_mod.helper_b") == "pkg_mod.helper_a"


def test_pt301_thread_target_closure_not_attributed_to_caller(tmp_path):
    """Review regression: a nested def handed to Thread(target=...)
    runs LATER on another thread with nothing held — its acquires must
    not fold into the enclosing method's transitive lockset (false
    A->B edge), while a SYNCHRONOUS nested-def call must still count."""
    findings, checker = _lock_check(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def start(self):
                def _worker():
                    with self._b:
                        pass
                with self._a:
                    threading.Thread(target=_worker).start()

            def other(self):
                with self._b:
                    with self._a:
                        pass
    """)
    # no a->b edge from the closure => no cycle with other()'s b->a
    assert findings == [], [str(f) for f in findings]
    findings, _ = _lock_check(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def start(self):
                def _helper():
                    with self._b:
                        pass
                with self._a:
                    _helper()          # synchronous: edge a->b is real

            def other(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert "PT301" in [f.rule for f in findings]


def test_pt301_multi_item_with_keeps_held_for_later_items(tmp_path):
    """Review regression: in `with self._a, make():` the make() call
    runs with _a already held — its transitive locks must edge."""
    findings, _ = _lock_check(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a, self.make():
                    pass

            def make(self):
                with self._b:
                    return open("/dev/null")

            def rev(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert "PT301" in [f.rule for f in findings]


def test_lockcheck_detects_three_lock_cycle():
    """Review regression: the tracker's contract is cycles, not just
    2-lock inversions — A->B, B->C recorded, then C->A must raise."""
    with lockcheck.tracking():
        a = threading.Lock()
        b = threading.Lock()
        c = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(lockcheck.LockOrderError):
            with c:
                with a:
                    pass


def test_lockcheck_env_zero_means_off(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_LOCKCHECK", "0")
    lockcheck.maybe_install_from_env()
    assert not lockcheck.installed()
    monkeypatch.setenv("PADDLE_TPU_LOCKCHECK", "1")
    lockcheck.maybe_install_from_env()
    try:
        assert lockcheck.installed()
    finally:
        lockcheck.uninstall()
        lockcheck.reset()


def test_pt100_parse_failure_has_own_rule(tmp_path):
    path = tmp_path / "tools" / "broken.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("def broken(:\n")
    findings, _ = run_pass1(str(tmp_path), paths=[str(path)])
    assert [f.rule for f in findings] == ["PT100"]


def test_lockcheck_cross_thread_release_no_stale_held():
    """Review regression: threading.Lock legally releases from another
    thread (handoff pattern); the entry must come off the ACQUIRER's
    held stack, or every later acquire in that thread records edges
    from a lock it no longer holds (spurious LockOrderError)."""
    with lockcheck.tracking():
        handoff = threading.Lock()
        other = threading.Lock()
        handoff.acquire()          # main thread acquires

        t = threading.Thread(target=handoff.release)  # other releases
        t.start()
        t.join()
        assert handoff not in lockcheck._STATE.held(), \
            "stale held entry after cross-thread release"
        # no bogus handoff->other edge from this acquire (edges from
        # handoff to Thread-internal locks taken during t.start() are
        # real — main DID hold handoff then)
        with other:
            pass
        assert (handoff.site, other.site) not in lockcheck.edges(), \
            "edge recorded from a released lock"


def test_pt302_silent_for_rlock(tmp_path):
    findings, _ = _lock_check(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """)
    assert findings == []


def test_pass3_repo_scope_covers_the_five_threaded_modules():
    findings, checker = run_pass3(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert findings == []
    covered = set(checker.modules)
    for mod in ("paddle_tpu/serving/batcher.py",
                "paddle_tpu/dist/master.py",
                "paddle_tpu/dist/checkpoint.py",
                "paddle_tpu/trainer/checkpoint.py",
                "paddle_tpu/data/prefetch.py"):
        assert mod in covered
    # the graph is real: the engine lock is ordered before the metrics
    # lock, and the master's RLock before its store/chaos locks
    idents = {a.rsplit(".", 1)[-1] + "->" + b.rsplit(".", 1)[-1]
              for a, b in checker.edges}
    assert len(checker.locks) >= 8


# ---------------------------------------------------- runtime tracker
def test_lockcheck_detects_inversion_deterministically():
    with lockcheck.tracking():
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with pytest.raises(lockcheck.LockOrderError):
            with b:
                with a:
                    pass


def test_lockcheck_self_deadlock_warns_and_handoff_completes():
    """A holder's blocking re-acquire WARNS (real self-deadlocks hang
    at the warned line) but must complete under a legal cross-thread
    handoff release — raising here would fail correct rendezvous code
    process-wide (review round 7)."""
    with lockcheck.tracking():
        lk = threading.Lock()
        with pytest.warns(lockcheck.SelfDeadlockWarning):
            lk.acquire()
            import time
            releaser = threading.Thread(
                target=lambda: (time.sleep(0.05), lk.release()))
            releaser.start()
            lk.acquire()       # warned; completes after the handoff
            releaser.join()
        lk.release()


def test_lockcheck_condition_composes():
    with lockcheck.tracking():
        cond = threading.Condition(threading.Lock())
        hit = []

        def waiter():
            with cond:
                cond.wait(timeout=2.0)
                hit.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.05)
        with cond:
            cond.notify_all()
        t.join(timeout=5.0)
        assert hit == [1]


def test_lockcheck_pr6_masterclient_bug_class_regression():
    """The PR 6 bug class: MasterClient's RPC exchange vs its heartbeat
    thread. Pre-fix, the exchange path and the teardown/bookkeeping
    path touched the socket state under DIFFERENT lock orders, cross-
    wiring one thread's response into another. Reintroduce the shape —
    call() takes sock-lock then state-lock, heartbeat takes state-lock
    then sock-lock — and the tracker must fail the test, from a SINGLE
    interleaving, no lucky race needed."""
    with lockcheck.tracking():

        class BuggyClient:
            def __init__(self):
                self._sock_lock = threading.Lock()
                self._state_lock = threading.Lock()
                self.desynced = False

            def call(self):
                with self._sock_lock:      # exchange scope
                    with self._state_lock:  # records seq numbers
                        pass

            def heartbeat_teardown(self):
                # the buggy order: bookkeeping first, socket second
                with self._state_lock:
                    with self._sock_lock:
                        self.desynced = True

        c = BuggyClient()
        c.call()
        with pytest.raises(lockcheck.LockOrderError):
            c.heartbeat_teardown()


def test_lockcheck_tracking_restores_prior_install_state():
    """Review regression: a tracking() block inside a process armed
    via PADDLE_TPU_LOCKCHECK must not disarm it on exit (and nested
    blocks must not disarm the outer one)."""
    lockcheck.install()
    try:
        with lockcheck.tracking():
            with lockcheck.tracking():
                assert lockcheck.installed()
            assert lockcheck.installed()
        assert lockcheck.installed(), \
            "tracking() disarmed the process-wide install"
    finally:
        lockcheck.uninstall()
        lockcheck.reset()
    with lockcheck.tracking():
        assert lockcheck.installed()
    assert not lockcheck.installed()  # this block DID own the install


def test_stale_baseline_with_unknown_rule_reports_not_crashes(tmp_path):
    """Review regression: a typo'd rule id in a stale baseline entry
    must come back as a printed finding (exit 1), not a KeyError on
    the report path."""
    from paddle_tpu.analysis.__main__ import run
    bl = tmp_path / "baseline.toml"
    bl.write_text('[[suppress]]\nrule = "PT1O4"\n'  # letter O typo
                  'reason = "typo on purpose"\n')
    rc = run(["--skip-jaxpr", "--baseline", str(bl)])
    assert rc == 1
    # a typo'd SHORT NAME can match no pass ever — it must be
    # reported stale on every run, including --fast (review round 4)
    bl.write_text('[[suppress]]\nrule = "unguarded-jits"\n'
                  'reason = "typo on purpose"\n')
    rc = run(["--skip-jaxpr", "--baseline", str(bl)])
    assert rc == 1


def test_lockcheck_condition_on_recursively_held_rlock():
    """Review regression: Condition.wait() on a tracked RLock held at
    TWO recursion levels must release both (via forwarded
    _release_save) so a notifier can acquire and wake the waiter —
    without the forwarding, the tracker itself deadlocked code that is
    correct untracked."""
    with lockcheck.tracking():
        cond = threading.Condition(threading.RLock())
        woke = []

        def waiter():
            with cond:
                with cond:           # second recursion level
                    if cond.wait(timeout=5.0):
                        woke.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.1)
        got = cond.acquire(timeout=3.0)  # fails if wait kept a level
        assert got, "notifier could not acquire: wait() kept the lock"
        cond.notify_all()
        cond.release()
        t.join(timeout=5.0)
        assert woke == [1]
        assert cond._lock not in lockcheck._STATE.held()


def test_lockcheck_clean_on_real_prefetch_pipeline():
    """Real threaded code under the tracker: a full prefetch pass
    (worker thread + bounded queue + consumer) records edges but no
    inversion."""
    with lockcheck.tracking():
        from paddle_tpu.data.prefetch import PrefetchPipeline

        def reader():
            return iter([[1, 2], [3, 4], [5, 6]])

        got = list(PrefetchPipeline(reader, feeder=lambda b: b,
                                    place=False))
        assert got == [[1, 2], [3, 4], [5, 6]]


# ------------------------------------------------------ PT401 schema
def test_pt401_schema_good_and_bad(tmp_path):
    good = tmp_path / "BENCH_good.json"
    good.write_text(json.dumps({
        "metric": "x_ab", "platform": "cpu",
        "a_steps_per_sec": 10.0, "b_steps_per_sec": 5.0,
        "a_vs_b": 2.0}))
    assert check_bench_file(str(good), "BENCH_good.json") == []

    bad = tmp_path / "BENCH_bad.json"
    bad.write_text("{truncated")
    fs = check_bench_file(str(bad), "BENCH_bad.json")
    assert [f.rule for f in fs] == ["PT401"]

    nan = tmp_path / "BENCH_nan.json"
    nan.write_text('{"metric": "m", "platform": "cpu", '
                   '"a": 1.0, "b": 2.0, "a_vs_b": NaN}')
    fs = check_bench_file(str(nan), "BENCH_nan.json")
    assert any("non-finite" in f.message for f in fs)

    shapeless = tmp_path / "BENCH_shapeless.json"
    shapeless.write_text('{"hello": 1}')
    fs = check_bench_file(str(shapeless), "BENCH_shapeless.json")
    assert any("unrecognized" in f.message for f in fs)

    # ratio without its sides: best-of evidence not re-checkable
    lonely = tmp_path / "BENCH_lonely.json"
    lonely.write_text('{"metric": "m", "platform": "cpu", '
                      '"a_vs_b": 2.0}')
    fs = check_bench_file(str(lonely), "BENCH_lonely.json")
    assert any("lacks its two sides" in f.message for f in fs)


def test_pt401_fleet_artifact_requires_failover_evidence(tmp_path):
    """The r13 fleet generation: a serving_fleet artifact must carry the
    cold-start A/B sides, the fleet p99, and the failover / zero-drop
    counters — a kill-and-respawn bench that recorded none of them is
    not evidence."""
    good = tmp_path / "BENCH_fleet.json"
    good.write_text(json.dumps({
        "metric": "serving_fleet_failover_and_aot_cold_start",
        "platform": "cpu",
        "cold_start_live_ms": 500.0, "cold_start_cache_ms": 25.0,
        "cold_start_live_vs_cache": 20.0,
        "fleet_p99_ms": 8.0, "fleet_failovers_total": 3,
        "fleet_failed_non_shed": 0}))
    assert check_bench_file(str(good), "BENCH_fleet.json") == []

    # missing the zero-drop counter and one cold-start side
    bad = tmp_path / "BENCH_fleet_bad.json"
    bad.write_text(json.dumps({
        "metric": "serving_fleet_failover_and_aot_cold_start",
        "platform": "cpu",
        "cold_start_live_ms": 500.0, "fleet_p99_ms": 8.0,
        "fleet_failovers_total": 3}))
    fs = check_bench_file(str(bad), "BENCH_fleet_bad.json")
    assert any("cold_start_cache_ms" in f.message for f in fs)
    assert any("fleet_failed_non_shed" in f.message for f in fs)

    # the committed artifact itself stays valid
    import os as _os
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    r13 = _os.path.join(root, "BENCH_r13.json")
    assert check_bench_file(r13, "BENCH_r13.json") == []


def test_pt401_autoscale_artifact_requires_trajectory_evidence(tmp_path):
    """The r14 self-operating-fleet generation: a serving_fleet_autoscale
    artifact must carry the replica-count trajectory, the ramp p99, and
    the zero-failed counter summed across rounds — an autoscale claim
    without the count actually following load is not evidence. The base
    serving_fleet keys are still required (it IS a fleet artifact)."""
    base = {
        "metric": "serving_fleet_autoscale_ha_failover",
        "platform": "cpu",
        "cold_start_live_ms": 500.0, "cold_start_cache_ms": 25.0,
        "cold_start_live_vs_cache": 20.0,
        "fleet_p99_ms": 8.0, "fleet_failovers_total": 1,
        "fleet_failed_non_shed": 0}
    good = tmp_path / "BENCH_auto.json"
    good.write_text(json.dumps(dict(
        base, autoscale_replica_trajectory=[1, 2, 3, 3, 2, 1],
        autoscale_p99_ms=40.0)))
    assert check_bench_file(str(good), "BENCH_auto.json") == []

    # a trajectory that is not a list of counts, and a missing p99
    bad = tmp_path / "BENCH_auto_bad.json"
    bad.write_text(json.dumps(dict(
        base, autoscale_replica_trajectory="1->3->1")))
    fs = check_bench_file(str(bad), "BENCH_auto_bad.json")
    assert any("autoscale_replica_trajectory" in f.message for f in fs)
    assert any("autoscale_p99_ms" in f.message for f in fs)

    # an r13-generation metric stays exempt from the autoscale keys
    old = tmp_path / "BENCH_old.json"
    old.write_text(json.dumps(dict(
        base, metric="serving_fleet_failover_and_aot_cold_start")))
    assert check_bench_file(str(old), "BENCH_old.json") == []

    # the committed r14 artifact itself carries the evidence
    import os as _os
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    r14 = _os.path.join(root, "BENCH_r14.json")
    assert check_bench_file(r14, "BENCH_r14.json") == []
    data = json.loads(open(r14).read())
    traj = data["autoscale_replica_trajectory"]
    assert data["fleet_failed_non_shed"] == 0
    assert min(traj) >= 1 and max(traj) > min(traj)


def test_pt401_overlap_artifact_requires_exposed_comm_evidence(tmp_path):
    """The r18 FSDP-overlap generation: an ``overlap*`` metric must
    carry both step-time sides AND the exposed-collective split (count
    + fraction per side) — on a 1-core host the step-time ratio is
    dispatch-bound noise, so the structural exposed-comm numbers ARE
    the overlap evidence; an artifact without them recorded nothing."""
    good = tmp_path / "BENCH_ov.json"
    good.write_text(json.dumps({
        "metric": "overlap_fsdp_fused_ab", "platform": "cpu",
        "overlap_on_steps_per_sec": 14.5,
        "overlap_off_steps_per_sec": 12.8,
        "overlap_vs_sync_steps": 1.13,
        "exposed_collectives_overlap_on": 2,
        "exposed_collectives_overlap_off": 14,
        "exposed_comm_frac_overlap_on": 0.143,
        "exposed_comm_frac_overlap_off": 1.0}))
    assert check_bench_file(str(good), "BENCH_ov.json") == []

    # missing one step-time side; collective count recorded as a string
    bad = tmp_path / "BENCH_ov_bad.json"
    bad.write_text(json.dumps({
        "metric": "overlap_fsdp_fused_ab", "platform": "cpu",
        "overlap_on_steps_per_sec": 14.5,
        "exposed_collectives_overlap_on": "2",
        "exposed_collectives_overlap_off": 14,
        "exposed_comm_frac_overlap_on": 0.143,
        "exposed_comm_frac_overlap_off": 1.0}))
    fs = check_bench_file(str(bad), "BENCH_ov_bad.json")
    assert any("overlap_off_steps_per_sec" in f.message for f in fs)
    assert any("exposed_collectives_overlap_on" in f.message for f in fs)

    # a non-overlap metric stays exempt from the overlap keys
    other = tmp_path / "BENCH_other.json"
    other.write_text(json.dumps(
        {"metric": "fsdp_full_param_sharding_ab", "platform": "cpu"}))
    assert check_bench_file(str(other), "BENCH_other.json") == []

    # the committed r18 artifact itself carries the evidence, and the
    # overlap side exposes strictly fewer collectives
    import os as _os
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    r18 = _os.path.join(root, "BENCH_r18.json")
    assert check_bench_file(r18, "BENCH_r18.json") == []
    data = json.loads(open(r18).read())
    assert (data["exposed_collectives_overlap_on"]
            < data["exposed_collectives_overlap_off"])
    assert data["overlap_bitwise_identical"] is True


def test_pt401_quant_artifact_requires_gate_evidence(tmp_path):
    """The r19 quantized-serving generation: a ``serving_quant*``
    metric must carry all three precision sides, FINITE gate deltas,
    and the bool gate verdict — a quantization speedup for a model
    whose accuracy gate never replayed (or failed) is not evidence."""
    base = {"metric": "serving_quant_ab", "platform": "cpu",
            "quant_fp32_p50_ms": 1.0, "quant_bf16_p50_ms": 0.9,
            "quant_int8_p50_ms": 0.8,
            "quant_bf16_vs_fp32": 0.9, "quant_int8_vs_fp32": 0.8,
            "quant_gate_delta_bf16": 1e-4,
            "quant_gate_delta_int8": 5e-4,
            "quant_gate_passed": True}
    good = tmp_path / "BENCH_q.json"
    good.write_text(json.dumps(base))
    assert check_bench_file(str(good), "BENCH_q.json") == []

    # missing the int8 side + the verdict; a NaN gate delta
    bad = dict(base)
    del bad["quant_int8_p50_ms"], bad["quant_gate_passed"]
    badf = tmp_path / "BENCH_q_bad.json"
    badf.write_text(json.dumps(bad).replace("0.0001", "NaN"))
    fs = check_bench_file(str(badf), "BENCH_q_bad.json")
    assert any("quant_int8_p50_ms" in f.message for f in fs)
    assert any("quant_gate_passed" in f.message for f in fs)
    assert any("non-finite" in f.message for f in fs)

    # a non-quant serving metric stays exempt
    other = tmp_path / "BENCH_o.json"
    other.write_text(json.dumps(
        {"metric": "serving_dynamic_batching_ab", "platform": "cpu"}))
    assert check_bench_file(str(other), "BENCH_o.json") == []

    # the committed r19 artifact itself carries the evidence: three
    # distinct versions, gates green, deltas inside tolerance
    import os as _os
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    r19 = _os.path.join(root, "BENCH_r19.json")
    assert check_bench_file(r19, "BENCH_r19.json") == []
    data = json.loads(open(r19).read())
    assert data["quant_gate_passed"] is True
    assert data["quant_gate_delta_bf16"] <= data["quant_gate_tol_bf16"]
    assert data["quant_gate_delta_int8"] <= data["quant_gate_tol_int8"]
    assert len(set(data["quant_model_versions"].values())) == 3


def test_pt401_serve_train_artifact_requires_learning_evidence(tmp_path):
    """The r20 online-learning generation: a ``serve_train*`` metric
    must carry the held-out error trajectory (one finite point per
    published version), the zero-drop counter summed over every round,
    and the publish/rollback ledger — an online loop that published
    nothing, learned nothing, or dropped requests mid-swap is not
    evidence."""
    base = {"metric": "serve_train_loop", "platform": "cpu",
            "serve_train_error_trajectory": [0.48, 0.41, 0.37],
            "fleet_failed_non_shed": 0,
            "publishes_total": 3, "rollbacks_total": 1}
    good = tmp_path / "BENCH_st.json"
    good.write_text(json.dumps(base))
    assert check_bench_file(str(good), "BENCH_st.json") == []

    # an empty trajectory, a missing drop counter, a bool counter
    bad = dict(base)
    bad["serve_train_error_trajectory"] = []
    del bad["fleet_failed_non_shed"]
    bad["publishes_total"] = True
    badf = tmp_path / "BENCH_st_bad.json"
    badf.write_text(json.dumps(bad))
    fs = check_bench_file(str(badf), "BENCH_st_bad.json")
    assert any("serve_train_error_trajectory" in f.message for f in fs)
    assert any("fleet_failed_non_shed" in f.message for f in fs)
    assert any("publishes_total" in f.message for f in fs)

    # a NaN trajectory point is caught by the global finite-number
    # walk (json.loads admits NaN literals)
    nanf = tmp_path / "BENCH_st_nan.json"
    nanf.write_text(json.dumps(base).replace("0.41", "NaN"))
    fs = check_bench_file(str(nanf), "BENCH_st_nan.json")
    assert any("non-finite" in f.message for f in fs)

    # the serving_* prefixes do not capture serve_train and vice versa
    other = tmp_path / "BENCH_sv.json"
    other.write_text(json.dumps(
        {"metric": "serving_dynamic_batching_ab", "platform": "cpu"}))
    assert check_bench_file(str(other), "BENCH_sv.json") == []

    # the committed r20 artifact itself carries the evidence: the
    # held-out error falls across >= 2 published versions, the fleet
    # dropped nothing, and at least one rollback drill is on record
    import os as _os
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    r20 = _os.path.join(root, "BENCH_r20.json")
    assert check_bench_file(r20, "BENCH_r20.json") == []
    data = json.loads(open(r20).read())
    traj = data["serve_train_error_trajectory"]
    assert len(traj) >= 2 and traj[-1] < traj[0]
    assert data["fleet_failed_non_shed"] == 0
    assert data["publishes_total"] >= 2


def test_pt401_workload_artifact_family(tmp_path):
    """The r21 trace family: a ``WORKLOAD_*`` artifact must be
    replayable by construction — non-empty monotone events carrying
    the full replay key set, with ``n_events`` matching."""
    events = [{"t": 0.0, "kind": "score", "sample": [[0.1, 0.2], 1],
               "deadline_ms": None, "beam_size": None,
               "max_length": None, "outcome": "admitted"},
              {"t": 0.05, "kind": "generate", "sample": [[1.0, -1.0]],
               "deadline_ms": 50.0, "beam_size": 2,
               "max_length": 16, "outcome": "overloaded"}]
    base = {"workload": "mix", "version": 1, "n_events": 2,
            "duration_s": 0.05, "events": events}
    good = tmp_path / "WORKLOAD_good.json"
    good.write_text(json.dumps(base))
    assert check_bench_file(str(good), "WORKLOAD_good.json") == []

    # truncation, a shuffled offset, a missing replay key, a bad kind
    bad = dict(base, n_events=4,
               events=[dict(events[1], t=0.05),
                       dict(events[0], t=0.0, kind="mystery"),
                       {"t": 0.1, "kind": "score"}])
    badf = tmp_path / "WORKLOAD_bad.json"
    badf.write_text(json.dumps(bad))
    fs = check_bench_file(str(badf), "WORKLOAD_bad.json")
    assert {f.rule for f in fs} == {"PT401"}
    assert any("n_events" in f.message for f in fs)
    assert any("monotone arrival" in f.message for f in fs)
    assert any("missing replay key" in f.message for f in fs)
    assert any("unknown kind" in f.message for f in fs)

    empty = tmp_path / "WORKLOAD_empty.json"
    empty.write_text(json.dumps(dict(base, events=[], n_events=0)))
    fs = check_bench_file(str(empty), "WORKLOAD_empty.json")
    assert any("non-empty 'events'" in f.message for f in fs)


def test_pt401_autotune_artifact_joins_trace_to_score(tmp_path):
    """The r21 tune-score family: a ``serving_autotune*`` metric must
    JOIN to the traces it replayed (the cited ``WORKLOAD_*.json`` files
    exist beside it), carry both A/B score sides per mix, keep each
    mix's replay drift inside its own declared bound, and sum the
    zero-drop counter over every replay."""
    trace = {"workload": "short_burst", "version": 1, "n_events": 1,
             "duration_s": 0.0,
             "events": [{"t": 0.0, "kind": "score",
                         "sample": [[0.1], 1], "deadline_ms": None,
                         "beam_size": None, "max_length": None,
                         "outcome": "admitted"}]}
    (tmp_path / "WORKLOAD_r21_short_burst.json").write_text(
        json.dumps(trace))
    base = {"metric": "serving_autotune_ab", "platform": "cpu",
            "autotune_mixes": ["short_burst"],
            "autotune_workloads": ["WORKLOAD_r21_short_burst.json"],
            "autotune_drift_bound": 0.25,
            "autotune_short_burst_default_score": 0.44,
            "autotune_short_burst_tuned_score": 1.0,
            "autotune_short_burst_tuned_vs_default_score": 2.29,
            "autotune_short_burst_replay_drift": 0.0,
            "fleet_failed_non_shed": 0}
    good = tmp_path / "BENCH_at.json"
    good.write_text(json.dumps(base))
    assert check_bench_file(str(good), "BENCH_at.json") == []

    # a dangling trace join, a drift past the declared bound, a
    # missing A/B side, a missing drop counter
    bad = dict(base)
    bad["autotune_workloads"] = ["WORKLOAD_r21_gone.json"]
    bad["autotune_short_burst_replay_drift"] = 0.5
    del bad["autotune_short_burst_default_score"]
    del bad["fleet_failed_non_shed"]
    badf = tmp_path / "BENCH_at_bad.json"
    badf.write_text(json.dumps(bad))
    fs = check_bench_file(str(badf), "BENCH_at_bad.json")
    assert {f.rule for f in fs} == {"PT401"}
    assert any("does not exist beside it" in f.message for f in fs)
    assert any("exceeds its own declared bound" in f.message for f in fs)
    assert any("default_score" in f.message for f in fs)
    assert any("fleet_failed_non_shed" in f.message for f in fs)

    # the committed r21 artifact itself carries the tentpole evidence:
    # both mixes' traces join, the tuned config beats the hand-set
    # defaults on the declared SLO score on BOTH mixes, the replays
    # dropped nothing anywhere, and the determinism drift stayed
    # inside the declared bound (also pinned by the schema above)
    import os as _os
    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    r21 = _os.path.join(root, "BENCH_r21.json")
    assert check_bench_file(r21, "BENCH_r21.json") == []
    data = json.loads(open(r21).read())
    assert len(data["autotune_mixes"]) >= 2
    for m in data["autotune_mixes"]:
        assert (data[f"autotune_{m}_tuned_score"]
                > data[f"autotune_{m}_default_score"])
        assert (data[f"autotune_{m}_replay_drift"]
                <= data["autotune_drift_bound"])
    assert data["fleet_failed_non_shed"] == 0
    for w in data["autotune_workloads"]:
        assert check_bench_file(_os.path.join(root, w), w) == []


def test_pass4_overlap_spelling_budgets_identically():
    """The sync->async flip must budget IDENTICALLY: the overlap chain
    is an ``optimization_barrier`` spelling of the SAME gathers, so the
    pass-4 collective manifest of the pinned fsdp programs — op counts,
    axes, byte volumes — is byte-identical with the chain forced on,
    and ``comm_budget.toml`` needs no edit. This is the regression
    fence for anyone 'optimizing' the chain into extra collectives."""
    import jax

    from paddle_tpu.analysis import shard_audit as sa
    from paddle_tpu.optim import zero1

    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs the 8-device virtual mesh")
    entries = sa.load_budget()
    for build in (sa.build_fsdp_train, sa.build_fsdp_pipe):
        with zero1.overlap_spelling("off"):
            base = sa.compile_program(build())
        with zero1.overlap_spelling("force"):
            forced = sa.compile_program(build())
        m_sync = sa.collect_manifest(base.hlo, base.spec.mesh)
        m_over = sa.collect_manifest(forced.hlo, forced.spec.mesh)
        assert m_sync == m_over, (
            f"{base.spec.name}: overlap spelling changed the collective "
            f"manifest\n sync: {sa.format_manifest(m_sync)}\n"
            f" over: {sa.format_manifest(m_over)}")
        # and the forced program still lands ON the pinned budget
        findings, _ = sa.check_budget(
            forced.spec.name, m_over, entries, forced.spec.anchor,
            "analysis/comm_budget.toml")
        assert findings == [], [f.message for f in findings]


# ----------------------------------------------------------- baseline
def test_baseline_parse_apply_and_stale(tmp_path):
    bl = tmp_path / "baseline.toml"
    bl.write_text(textwrap.dedent("""
        # comment
        [[suppress]]
        rule = "PT104"
        path = "paddle_tpu/models/gan.py"
        line = 78
        reason = "parked for the example"

        [[suppress]]
        rule = "jit-closure-capture"
        path = "paddle_tpu/x.py"
        reason = "stale entry"
    """))
    entries = load_baseline(str(bl))
    assert len(entries) == 2
    findings = [Finding("PT104", "paddle_tpu/models/gan.py", 78, "m")]
    kept, suppressed, stale = apply_baseline(findings, entries)
    assert kept == [] and suppressed == 1
    assert len(stale) == 1 and stale[0].path == "paddle_tpu/x.py"


def test_stale_baseline_scoped_to_passes_that_ran(tmp_path):
    """Review regression: a baselined PT2xx entry must not read as
    STALE when the jaxpr pass was skipped (--fast), or the fast and
    full CI paths could never both be green with a non-empty
    baseline."""
    from paddle_tpu.analysis.__main__ import run
    bl = tmp_path / "baseline.toml"
    bl.write_text('[[suppress]]\nrule = "PT202"\n'
                  'reason = "parked pending donation fix"\n')
    rc = run(["--skip-jaxpr", "--baseline", str(bl)])
    assert rc == 0  # unused PT202 entry, but its pass did not run
    bl.write_text('[[suppress]]\nrule = "PT401"\n'
                  'path = "BENCH_never_existed.json"\n'
                  'reason = "stale on purpose"\n')
    rc = run(["--skip-jaxpr", "--baseline", str(bl)])
    assert rc == 1  # schema pass ran; its stale entry is a finding


def test_baseline_rejects_reasonless_entries(tmp_path):
    bl = tmp_path / "baseline.toml"
    bl.write_text('[[suppress]]\nrule = "PT104"\n')
    with pytest.raises(ValueError, match="reason"):
        load_baseline(str(bl))


# ------------------------------------------------- masks.py satellite
def test_assert_mask_f32_two_sided():
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.utils.masks import (MaskDtypeError, assert_mask_f32,
                                        assert_feed_masks_f32)
    ok = jnp.ones((2, 3), jnp.float32)
    assert assert_mask_f32(ok) is ok
    assert assert_mask_f32(None) is None
    # the invariant is "never BELOW f32": float64 (numpy's default,
    # canonicalized by jax), int and bool masks carry full count
    # precision and must pass — only the saturating floats reject
    assert_mask_f32(np.ones((2, 3)))              # float64
    assert_mask_f32(np.ones((2, 3), np.int32))
    assert_mask_f32(np.ones((2, 3), bool))
    with pytest.raises(MaskDtypeError):
        assert_mask_f32(jnp.ones((2, 3), jnp.bfloat16))
    with pytest.raises(MaskDtypeError):
        assert_mask_f32(np.ones((2, 3), np.float16))
    feed = {"x": Argument(value=jnp.ones((2, 3)), mask=ok)}
    assert assert_feed_masks_f32(feed) is feed
    bad = {"x": Argument(value=jnp.ones((2, 3)),
                         mask=jnp.ones((2, 3), jnp.bfloat16))}
    with pytest.raises(MaskDtypeError, match="x"):
        assert_feed_masks_f32(bad)


def test_cast_compute_rejects_bf16_mask_at_trace_time():
    """The trainer-side wiring: a sub-f32 mask entering _cast_compute
    raises immediately (trace time), not after a saturated sum."""
    from paddle_tpu.config import dsl
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGD
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.utils.masks import MaskDtypeError

    dsl.reset()
    x = dsl.data(name="x", size=4, is_sequence=True)
    lab = dsl.data(name="label", size=2)
    pooled = dsl.pooling(input=x, pooling_type="avg", name="pool")
    out = dsl.fc(input=pooled, size=2, act="softmax", name="out")
    cost = dsl.classification_cost(input=out, label=lab)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
             compute_dtype="bfloat16")
    feed = {"x": Argument(value=jnp.ones((2, 3, 4)),
                          mask=jnp.ones((2, 3), jnp.bfloat16)),
            "label": Argument(value=jnp.zeros((2,), jnp.int32))}
    with pytest.raises(MaskDtypeError):
        tr._cast_compute(feed)


# ================================================= pass 4 (PT501-PT505)
# The sharding & collective-communication audit: every rule gets its
# known-bad fixture + known-good twin, against the same machinery the
# pass runs on the real parallel programs (shard_audit.py).

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from paddle_tpu.analysis import shard_audit as sa  # noqa: E402
from paddle_tpu.parallel.mesh import (create_mesh, rule_for,  # noqa: E402
                                      shard_map_compat)


def _mesh8():
    return create_mesh(n_data=8)


# ------------------------------------------------- budget file parsing
def test_comm_budget_parses_and_validates_entries(tmp_path):
    entry = ("[[collective]]\n"
             'program = "zero1"\n'
             'op = "all-gather"\n'
             'axis = "data"\n'
             "ops = 1\n"
             "bytes = 72384\n")
    p = tmp_path / "comm_budget.toml"
    p.write_text("# pinned\n" + entry)
    (e,) = sa.load_budget(str(p))
    assert e.key() == ("zero1", "all-gather", "data")
    assert (e.ops, e.bytes) == (1, 72384)
    p.write_text("[[collective]]\nops = 3\n")
    with pytest.raises(ValueError, match="program=, op= and axis="):
        sa.load_budget(str(p))
    p.write_text("[[collective]]\nprogram = ???\n")
    with pytest.raises(ValueError, match="unparseable"):
        sa.load_budget(str(p))
    # zero/omitted counts: pinning zero is spelled by entry ABSENCE —
    # a 0/0 entry would otherwise report as baffling 'GREW past 0 / 0'
    p.write_text(entry.replace("ops = 1", "ops = 0"))
    with pytest.raises(ValueError, match="deleting the entry"):
        sa.load_budget(str(p))
    p.write_text("\n".join(entry.splitlines()[:-1]) + "\n")  # no bytes=
    with pytest.raises(ValueError, match="deleting the entry"):
        sa.load_budget(str(p))
    # duplicate (program, op, axis): merge-conflict leftovers must not
    # silently resolve to whichever entry parses last
    p.write_text(entry + entry.replace("72384", "9"))
    with pytest.raises(ValueError, match="duplicate entry"):
        sa.load_budget(str(p))


def test_manifest_parses_hlo_groups_tuples_and_permutes():
    """Synthetic optimized-HLO lines: literal and iota replica groups
    map to mesh axes, tuple shapes sum bytes, async -done halves are
    not separate sites, permute pairs label their axis."""
    mesh = create_mesh(n_data=4, n_model=2)
    hlo = "\n".join([
        "  %ar = f32[16,16]{1,0} all-reduce(f32[16,16]{1,0} %x), "
        "channel_id=1, replica_groups={{0,2,4,6},{1,3,5,7}}, "
        "use_global_device_ids=true",
        "  %ag = (f32[8]{0}, f32[8]{0}) all-gather-start(%a, %b), "
        "replica_groups=[4,2]<=[8], dimensions={0}",
        "  %agd = (f32[8]{0}, f32[8]{0}) all-gather-done(%ag)",
        "  %cp = f32[4]{0} collective-permute(%c), "
        "source_target_pairs={{0,2},{2,4},{4,6},{6,0}}",
    ])
    manifest = sa.collect_manifest(hlo, mesh)
    assert manifest[("all-reduce", "data")] == [1, 16 * 16 * 4]
    # iota groups [4,2]<=[8] are {0,1},{2,3},... = the model axis;
    # the -done half of the async pair contributes no second site, and
    # the -start result tuple (operand, output) counts only the OUTPUT
    # half — the same collective budgets identically in either spelling
    assert manifest[("all-gather", "model")] == [1, 8 * 4]
    # pairs step flat ids by 2 = neighbors along the data axis
    assert manifest[("collective-permute", "data")] == [1, 4 * 4]
    assert len(manifest) == 3


# -------------------------------------------------------------- PT501
def _fixture_gather_program(mesh):
    """A tiny sharded program whose ONE collective is an added
    all-gather — the drift fixture of the acceptance criteria."""
    import jax

    def f(x):
        def local(s):
            return jax.lax.all_gather(s * 2.0, axis_name="data",
                                      axis=0, tiled=True)
        return shard_map_compat(local, mesh, in_specs=(P("data"),),
                                out_specs=P())(x)

    x = jax.device_put(jnp.ones((8, 4), jnp.float32),
                       NamedSharding(mesh, P("data")))
    hlo = jax.jit(f).lower(x).compile().as_text()
    return sa.collect_manifest(hlo, mesh)


def _entry(program, op, axis, ops, nbytes):
    e = sa.BudgetEntry()
    e.program, e.op, e.axis, e.ops, e.bytes = (program, op, axis, ops,
                                               nbytes)
    return e


def test_pt501_added_all_gather_is_unbudgeted_drift():
    manifest = _fixture_gather_program(_mesh8())
    ((kind, axis), (n, nbytes)) = next(iter(manifest.items()))
    assert (kind, axis, n) == ("all-gather", "data", 1)
    findings, used = sa.check_budget("fixture", manifest, [],
                                     "x.py", "comm_budget.toml")
    assert [f.rule for f in findings] == ["PT501"]
    assert "UNBUDGETED" in findings[0].message and used == []
    # good twin: the budget pins exactly what the program emits
    good = [_entry("fixture", "all-gather", "data", 1, nbytes)]
    findings, used = sa.check_budget("fixture", manifest, good,
                                     "x.py", "comm_budget.toml")
    assert findings == [] and used == [0]


def test_pt501_growth_and_shrink_both_flag():
    manifest = {("all-gather", "data"): [2, 1024]}
    grew = [_entry("p", "all-gather", "data", 1, 1024)]
    findings, _ = sa.check_budget("p", manifest, grew, "x.py", "b.toml")
    assert [f.rule for f in findings] == ["PT501"]
    assert "GREW" in findings[0].message
    # the only-shrinks side: an improvement must be locked in
    shrank = [_entry("p", "all-gather", "data", 4, 4096)]
    findings, _ = sa.check_budget("p", manifest, shrank, "x.py",
                                  "b.toml")
    assert [f.rule for f in findings] == ["PT501"]
    assert "SHRANK" in findings[0].message
    exact = [_entry("p", "all-gather", "data", 2, 1024)]
    findings, _ = sa.check_budget("p", manifest, exact, "x.py", "b.toml")
    assert findings == []


def test_pt501_stale_budget_entries_flag():
    entries = [_entry("zero1", "all-gather", "data", 1, 10),
               _entry("no_such_program", "all-reduce", "data", 1, 10)]
    findings = sa.stale_budget_findings(entries, {0}, "b.toml")
    assert [f.rule for f in findings] == ["PT501"]
    assert "unknown program" in findings[0].message
    findings = sa.stale_budget_findings(
        [_entry("zero1", "all-to-all", "data", 1, 10)], set(), "b.toml")
    assert "matches no collective" in findings[0].message


# -------------------------------------------------------------- PT502
def test_pt502_replicated_big_slot_flags_and_sharded_twin_passes():
    mesh = _mesh8()
    big_rep = jax.device_put(jnp.ones((256, 128)),
                             NamedSharding(mesh, P()))
    big_sharded = jax.device_put(jnp.ones((256, 128)),
                                 NamedSharding(mesh, P("data")))
    small_rep = jax.device_put(jnp.ones((8, 8)),
                               NamedSharding(mesh, P()))
    must = [("slot", lambda p: "'slots'" in p)]
    findings = sa.replication_findings(
        {"slots": {"w": big_rep}}, must, "fx", "x.py")
    assert [f.rule for f in findings] == ["PT502"]
    assert "FULLY REPLICATED" in findings[0].message
    assert "data(8)" in findings[0].message  # the matching axis named
    assert sa.replication_findings(
        {"slots": {"w": big_sharded}}, must, "fx", "x.py") == []
    # below BIG_BYTES is scaffolding, not model state
    assert sa.replication_findings(
        {"slots": {"w": small_rep}}, must, "fx", "x.py") == []
    # leaves outside the must-shard contract (e.g. dp params) pass
    assert sa.replication_findings(
        {"params": {"w": big_rep}}, must, "fx", "x.py") == []
    # no mesh axis divides any dim: replication is the legitimate
    # fallback (shard_opt_state's non-divisible warning path), not a
    # violation — review fix, the rule matches its documentation
    indivisible = jax.device_put(jnp.ones((255, 129)),
                                 NamedSharding(mesh, P()))
    assert sa.replication_findings(
        {"slots": {"w": indivisible}}, must, "fx", "x.py") == []


# -------------------------------------------------------------- PT503
def _pack_program(mesh, pin):
    def f(a, b):
        packed = jnp.concatenate([a, b], axis=0).reshape(8, -1)
        if pin:
            packed = jax.lax.with_sharding_constraint(
                packed, NamedSharding(mesh, P()))

        def local(x):
            return jax.lax.all_gather(x * 2.0, axis_name="data",
                                      axis=0, tiled=True)

        return shard_map_compat(local, mesh, in_specs=(P("data"),),
                                out_specs=P())(packed)

    return f


def test_pt503_unpinned_pack_flags_and_pinned_twin_passes():
    mesh = _mesh8()
    a = jnp.ones((8, 4))
    closed = jax.make_jaxpr(jax.jit(_pack_program(mesh, pin=False)))(a, a)
    findings = sa.shardmap_pin_findings(closed, "fx", "x.py")
    assert [f.rule for f in findings] == ["PT503"]
    assert "concatenate" in findings[0].message
    closed = jax.make_jaxpr(jax.jit(_pack_program(mesh, pin=True)))(a, a)
    assert sa.shardmap_pin_findings(closed, "fx", "x.py") == []


def test_pt503_deliberately_unpinned_zero1_pack(monkeypatch):
    """The acceptance fixture: the REAL ZeRO-1 train step with its
    with_sharding_constraint pins stripped (exactly the pre-r07-fix
    program) raises PT503; the shipped (pinned) step is its good
    twin."""
    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.data import DataFeeder, dense_vector, integer_value
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel import mesh as mesh_lib
    from paddle_tpu.trainer import SGD

    def build():
        dsl.reset()
        x = dsl.data(name="x", size=8)
        lab = dsl.data(name="label", size=2)
        h = dsl.fc(input=x, size=8, act="relu", name="h")
        out = dsl.fc(input=h, size=2, act="softmax", name="out")
        cost = dsl.classification_cost(input=out, label=lab)
        tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
                 mesh=_mesh8(), seed=0)
        tr.enable_zero1()
        feeder = DataFeeder({"x": dense_vector(8),
                             "label": integer_value(2)})
        rng = np.random.RandomState(0)
        data = [(rng.randn(8).astype(np.float32), int(rng.randint(2)))
                for _ in range(8)]
        feed = mesh_lib.shard_batch(feeder(data), tr.mesh)
        return tr, (tr.params, tr.opt_state, feed,
                    jax.random.PRNGKey(0), 0, None)

    tr, args = build()
    closed = jax.make_jaxpr(tr._train_step)(*args)
    assert sa.shardmap_pin_findings(closed, "zero1", "z.py") == []
    # strip the pins: trace again with the constraint a no-op
    monkeypatch.setattr(jax.lax, "with_sharding_constraint",
                        lambda x, s: x)
    tr2, args2 = build()
    closed = jax.make_jaxpr(tr2._train_step)(*args2)
    findings = sa.shardmap_pin_findings(closed, "zero1", "z.py")
    assert "PT503" in [f.rule for f in findings]


# -------------------------------------------------------------- PT504
def test_pt504_conflicting_pins_flag_and_single_pin_passes():
    mesh = _mesh8()

    def double(a):
        x = jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, P("data")))
        y = jax.lax.with_sharding_constraint(
            x.reshape(4, 16), NamedSharding(mesh, P()))
        return y * 1.0

    closed = jax.make_jaxpr(jax.jit(double))(jnp.ones((8, 8)))
    findings = sa.reshard_findings(closed, "fx", "x.py")
    assert [f.rule for f in findings] == ["PT504"]
    assert "re-pinned" in findings[0].message

    def single(a):
        x = jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, P("data")))
        return x * 1.0

    closed = jax.make_jaxpr(jax.jit(single))(jnp.ones((8, 8)))
    assert sa.reshard_findings(closed, "fx", "x.py") == []
    # re-pinning the SAME sharding is not a reshard

    def same(a):
        x = jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, P("data")))
        y = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("data")))
        return y * 1.0

    closed = jax.make_jaxpr(jax.jit(same))(jnp.ones((8, 8)))
    assert sa.reshard_findings(closed, "fx", "x.py") == []


# ------------------------------------------- PT505 + rule_for semantics
def test_rule_for_exact_beats_substring_regardless_of_order():
    """The precedence contract the pipeline/zero1 composition relies
    on: plan.shard_rules()'s '=<stacked key>' pins are merged AFTER
    user rules (trainer.py:enable_pipeline), and a broad user
    substring rule must not capture the stacked keys."""
    sub_first = {"blk": P("data"), "=_blk0.w0": P("pipe", None)}
    assert rule_for("_blk0.w0", sub_first) == P("pipe", None)
    exact_first = {"=_blk0.w0": P("pipe", None), "blk": P("data")}
    assert rule_for("_blk0.w0", exact_first) == P("pipe", None)
    # non-exact names still take the substring rule
    assert rule_for("_blk1.w0", sub_first) == P("data")


def test_rule_for_first_substring_match_wins_in_table_order():
    rules = {"emb": P("model", None), "w0": P("data")}
    assert rule_for("_emb.w0", rules) == P("model", None)
    assert rule_for("_out.w0", rules) == P("data")
    assert rule_for("_bias.b0", rules) == P()


def test_rule_for_exact_key_never_captures_superstring():
    rules = {"=_emb.w0": P("model", None)}
    assert rule_for("_emb.w0", rules) == P("model", None)
    assert rule_for("_user_emb.w0", rules) == P()


def test_effective_rules_respects_explicit_replication_request():
    """Review regression (round 3): a user's explicit P() rule on a
    sparse_grad table must keep it replicated — the sparse default may
    only fill in when NO key matches, or under exact-first precedence
    its auto-added '=' pin would override the user's substring rule."""
    from paddle_tpu.core.registry import ParamSpec
    from paddle_tpu.parallel.mesh import effective_rules

    mesh = create_mesh(n_data=4, n_model=2)
    spec = ParamSpec(shape=(64, 16), sparse_grad=True,
                     absolute_name="_emb.w0")
    # no user rule: the sparse default row-shards over model
    auto = effective_rules({"_emb.w0": spec}, mesh, None)
    assert rule_for("_emb.w0", auto) == P("model")
    # explicit P() replication request: no auto-pin may be added
    out = effective_rules({"_emb.w0": spec}, mesh, {"emb": P()})
    assert "=_emb.w0" not in out
    assert rule_for("_emb.w0", out) == P()


def test_pt505_bad_table_and_good_twin():
    names = ["_emb.w0", "_out.w0", "_blk0.w0"]
    bad = {
        "=_emb.w0": P("model", None),
        "_emb": P("data"),        # fully shadowed by the exact pin
        "conv": P("data"),        # dead: matches nothing
        "=_out": P("data"),       # exact key that exact-matches nothing
    }
    findings = sa.check_rule_table(bad, names, "x.py", "fixture")
    msgs = {f.message.split("rule key ")[1].split(" ")[0]: f.message
            for f in findings}
    assert all(f.rule == "PT505" for f in findings)
    assert "SHADOWED" in msgs["'_emb'"]
    assert "'=_emb.w0'" in msgs["'_emb'"]  # names the shadowing key
    assert "DEAD" in msgs["'conv'"]
    assert "exact-match key" in msgs["'=_out'"]
    assert len(findings) == 3
    good = {"=_emb.w0": P("model", None), "_out": P("data"),
            "blk": P("pipe", None)}
    assert sa.check_rule_table(good, names, "x.py", "fixture") == []
    # empty/None tables are vacuously clean
    assert sa.check_rule_table({}, names, "x.py", "fixture") == []
    assert sa.check_rule_table(None, names, "x.py", "fixture") == []


# ---------------------------------------- PT401 multichip / accuracy
def test_pt401_multichip_shape(tmp_path):
    good = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
            "tail": "dryrun ok"}
    p = tmp_path / "MULTICHIP_rXX.json"
    p.write_text(json.dumps(good))
    assert check_bench_file(str(p), "MULTICHIP_rXX.json") == []
    bad = dict(good)
    del bad["tail"]
    bad["ok"] = "yes"
    p.write_text(json.dumps(bad))
    findings = check_bench_file(str(p), "MULTICHIP_rXX.json")
    assert {f.rule for f in findings} == {"PT401"}
    assert any("'tail'" in f.message for f in findings)
    assert any("'ok'" in f.message for f in findings)


def test_pt401_accuracy_shape(tmp_path):
    good = {"platform": "cpu",
            "light_mnist": {"final_err": 0.08, "passes": 3}}
    p = tmp_path / "ACCURACY_rXX.json"
    p.write_text(json.dumps(good))
    assert check_bench_file(str(p), "ACCURACY_rXX.json") == []
    p.write_text(json.dumps({"platform": "cpu", "note": "nothing ran"}))
    findings = check_bench_file(str(p), "ACCURACY_rXX.json")
    assert [f.rule for f in findings] == ["PT401"]
    assert "run section" in findings[0].message
    # NaN anywhere still rejects (shared finite-number walk)
    p.write_text('{"platform": "cpu", "m": {"err": NaN}}')
    findings = check_bench_file(str(p), "ACCURACY_rXX.json")
    assert any("non-finite" in f.message for f in findings)


def test_pt401_family_keyed_by_filename_not_content(tmp_path):
    """Review regression: a truncated BENCH artifact that kept
    'platform' but lost 'metric' must fail as an unrecognized bench
    shape — not quietly validate against the looser accuracy schema;
    likewise a MULTICHIP file with accuracy-shaped content."""
    doc = json.dumps({"platform": "cpu", "zero1": {"steps_per_s": 12.0}})
    p = tmp_path / "BENCH_r99.json"
    p.write_text(doc)
    findings = check_bench_file(str(p), "BENCH_r99.json")
    assert [f.rule for f in findings] == ["PT401"]
    assert "unrecognized bench artifact shape" in findings[0].message
    p = tmp_path / "MULTICHIP_r99.json"
    p.write_text(doc)
    findings = check_bench_file(str(p), "MULTICHIP_r99.json")
    assert findings and all(f.rule == "PT401" for f in findings)
    assert any("n_devices" in f.message for f in findings)


def test_schema_check_scans_multichip_and_accuracy_patterns(tmp_path):
    from paddle_tpu.analysis.bench_schema import run_schema_check
    (tmp_path / "MULTICHIP_r99.json").write_text("{broken")
    (tmp_path / "ACCURACY_r99.json").write_text('{"platform": "cpu"}')
    findings = run_schema_check(str(tmp_path))
    assert sorted(f.path for f in findings) == [
        "ACCURACY_r99.json", "MULTICHIP_r99.json"]


# ------------------------------------------------------- --json mode
def test_json_output_round_trips_findings(tmp_path, capsys):
    """CI contract: --json emits ONE parseable JSON object on stdout
    (progress on stderr) whose findings mirror the text report's."""
    from paddle_tpu.analysis.__main__ import run
    (tmp_path / "BENCH_r99.json").write_text('{"metric": ""}')
    rc = run(["--root", str(tmp_path), "--json", "--skip-ast",
              "--skip-locks", "--skip-jaxpr", "--skip-shard",
              "--skip-mem"])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 1
    assert doc["counts"] == {"PT401": len(doc["findings"])}
    f = doc["findings"][0]
    assert f["rule"] == "PT401" and f["name"] == "bench-schema"
    assert f["file"] == "BENCH_r99.json" and f["line"] == 1
    assert "metric" in f["message"]
    # the same scan through the API agrees field by field
    from paddle_tpu.analysis.bench_schema import run_schema_check
    direct = run_schema_check(str(tmp_path))
    assert [(d["rule"], d["file"], d["line"], d["message"])
            for d in doc["findings"]] == \
        [(g.rule, g.path, g.line, g.message) for g in direct]


def test_json_output_exit2_still_emits_one_object(tmp_path, capsys):
    """Review regression: the exit-2 paths (audit crash, baseline load
    error) must still put ONE JSON object on stdout carrying the
    findings collected before the failure — `--json | jq .` always
    parses, per the documented contract."""
    from paddle_tpu.analysis.__main__ import run
    (tmp_path / "BENCH_r99.json").write_text('{"metric": ""}')
    bad_baseline = tmp_path / "baseline.toml"
    bad_baseline.write_text("[[suppress]]\nrule = ???\n")
    rc = run(["--root", str(tmp_path), "--json", "--skip-ast",
              "--skip-locks", "--skip-jaxpr", "--skip-shard",
              "--skip-mem",
              "--baseline", str(bad_baseline)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert "unparseable" in doc["error"]
    # the schema findings collected before the crash ride along
    assert doc["counts"] == {"PT401": len(doc["findings"])}
    assert doc["findings"][0]["file"] == "BENCH_r99.json"


def test_json_output_clean_tree_exits_zero(tmp_path, capsys):
    from paddle_tpu.analysis.__main__ import run
    (tmp_path / "BENCH_r99.json").write_text(
        '{"metric": "steps", "platform": "cpu", "a": 1.0, "b": 2.0}')
    rc = run(["--root", str(tmp_path), "--json", "--skip-ast",
              "--skip-locks", "--skip-jaxpr", "--skip-shard",
              "--skip-mem"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["findings"] == [] and doc["counts"] == {}
    assert doc["pass4_s"] is None  # pass 4 skipped: no wall time


# ======================================================= pass 5 (mem)
# Per-device memory-footprint audit: budget ratchet (PT601), scaling
# laws (PT602), donation honesty (PT603), temp blow-up (PT604), and
# the static-vs-runtime reconciliation (PT605).

from paddle_tpu.analysis import mem_audit as mem  # noqa: E402


def _mem_spec(fn, args, mesh, **kw):
    return sa.ProgramSpec("fixture", "x.py", fn, args, mesh, **kw)


def _mem_entry(program, **fields):
    e = mem.MemBudgetEntry()
    e.program = program
    for k, v in fields.items():
        setattr(e, k, v)
    return e


_GOOD_MEM_TOML = ("[[memory]]\n"
                  'program = "zero1"\n'
                  "arg_bytes = 100\n"
                  "out_bytes = 90\n"
                  "temp_bytes = 50\n"
                  "alias_bytes = 40\n"
                  "resident_bytes = 200\n"
                  "param_bytes = 60\n"
                  "slot_bytes = 20\n"
                  "act_bytes = 10\n")


def test_mem_budget_parses_and_validates_entries(tmp_path):
    p = tmp_path / "mem_budget.toml"
    p.write_text("# pinned\n" + _GOOD_MEM_TOML)
    (e,) = mem.load_mem_budget(str(p))
    assert (e.program, e.arg_bytes, e.resident_bytes) == ("zero1", 100,
                                                          200)
    # program is mandatory
    p.write_text("[[memory]]\narg_bytes = 1\n")
    with pytest.raises(ValueError, match="needs program="):
        mem.load_mem_budget(str(p))
    # arg_bytes >= 1: a zero means the pin was never generated
    p.write_text(_GOOD_MEM_TOML.replace("arg_bytes = 100",
                                        "arg_bytes = 0"))
    with pytest.raises(ValueError, match="arg_bytes >= 1"):
        mem.load_mem_budget(str(p))
    # the admission number must reconcile with its components
    p.write_text(_GOOD_MEM_TOML.replace("resident_bytes = 200",
                                        "resident_bytes = 150"))
    with pytest.raises(ValueError, match="reconcile with its "
                                         "components"):
        mem.load_mem_budget(str(p))
    # duplicate program: merge leftovers must not last-wins
    p.write_text(_GOOD_MEM_TOML + _GOOD_MEM_TOML)
    with pytest.raises(ValueError, match="duplicate entry"):
        mem.load_mem_budget(str(p))


# -------------------------------------------------------------- PT601
def _manifest(**over):
    m = {"arg_bytes": 100, "out_bytes": 90, "temp_bytes": 50,
         "alias_bytes": 40, "resident_bytes": 200, "param_bytes": 60,
         "slot_bytes": 20, "act_bytes": 10}
    m.update(over)
    return m


def test_pt601_growth_shrink_unpinned_and_exact():
    pinned = _mem_entry("p", arg_bytes=100, out_bytes=90, temp_bytes=50,
                        alias_bytes=40, resident_bytes=200,
                        param_bytes=60, slot_bytes=20, act_bytes=10)
    findings, used = mem.check_mem_budget("p", _manifest(), [pinned],
                                          "x.py", "mem_budget.toml")
    assert findings == [] and used == [0]
    # growth = drift, anchored at the program
    grew = _manifest(temp_bytes=51, resident_bytes=201)
    findings, _ = mem.check_mem_budget("p", grew, [pinned], "x.py",
                                       "mem_budget.toml")
    assert [f.rule for f in findings] == ["PT601", "PT601"]
    assert "temp_bytes GREW" in findings[0].message
    assert findings[0].path == "x.py"
    # unpinned shrinkage fails too — the win must be locked in
    shrank = _manifest(param_bytes=30, arg_bytes=70,
                       resident_bytes=170)
    findings, _ = mem.check_mem_budget("p", shrank, [pinned], "x.py",
                                       "mem_budget.toml")
    assert all(f.rule == "PT601" for f in findings)
    assert any("SHRANK" in f.message for f in findings)
    assert all(f.path == "mem_budget.toml" for f in findings)
    # a traced program with no entry at all is a finding (memory is
    # never zero — absence cannot mean "pinned empty" here)
    findings, used = mem.check_mem_budget("p", _manifest(), [], "x.py",
                                          "mem_budget.toml")
    assert [f.rule for f in findings] == ["PT601"] and used == []
    assert "UNPINNED" in findings[0].message


def test_pt601_stale_mem_budget_entries_flag():
    entries = [_mem_entry("zero1", arg_bytes=1),
               _mem_entry("no_such_program", arg_bytes=1)]
    findings = mem.stale_mem_budget_findings(entries, {0}, "b.toml")
    assert [f.rule for f in findings] == ["PT601"]
    assert "unknown program" in findings[0].message
    findings = mem.stale_mem_budget_findings(
        [_mem_entry("zero1", arg_bytes=1)], set(), "b.toml")
    assert "was not consumed" in findings[0].message


# -------------------------------------------------------------- PT602
def test_pt602_replicated_breaks_law_and_sharded_twin_holds():
    mesh = _mesh8()

    def f(w):
        return (w * 2.0).sum()

    law = [("slots shard ~1/8 over data", 0, None, 8, 1.1)]
    w_rep = jax.device_put(jnp.ones((256, 128)),
                           NamedSharding(mesh, P()))
    cp = sa.compile_program(_mem_spec(jax.jit(f), (w_rep,), mesh,
                                      mem_laws=law))
    findings = mem.scaling_findings(cp)
    assert [f.rule for f in findings] == ["PT602"]
    assert "VIOLATED" in findings[0].message
    w_sh = jax.device_put(jnp.ones((256, 128)),
                          NamedSharding(mesh, P("data")))
    cp = sa.compile_program(_mem_spec(jax.jit(f), (w_sh,), mesh,
                                      mem_laws=law))
    assert mem.scaling_findings(cp) == []
    # a law whose selector matches nothing is itself a finding — a
    # renamed key must not silently vacate the contract
    dead = [("law over nothing", 0, (lambda p: False), 8, 1.1)]
    cp = sa.compile_program(_mem_spec(jax.jit(f), (w_sh,), mesh,
                                      mem_laws=dead))
    findings = mem.scaling_findings(cp)
    assert [f.rule for f in findings] == ["PT602"]
    assert "selects no input leaf" in findings[0].message


# -------------------------------------------------------------- PT603
def test_pt603_dropped_donation_flags_and_donated_twin_passes():
    def f(x):
        return x + 1.0

    x = jnp.ones((64, 64))
    # good twin: donation reaches the compiled module's alias header
    cp = sa.compile_program(_mem_spec(
        jax.jit(f, donate_argnums=(0,)), (x,), None, donated=(0,)))
    manifest = mem.memory_manifest(cp)
    assert manifest["alias_bytes"] == 64 * 64 * 4
    assert mem.donation_findings(cp, manifest) == []
    # bad twin: the spec CLAIMS donation but the executable was built
    # without it — the annotation never reached compilation
    cp = sa.compile_program(_mem_spec(jax.jit(f), (x,), None,
                                      donated=(0,)))
    manifest = mem.memory_manifest(cp)
    assert manifest["alias_bytes"] == 0
    findings = mem.donation_findings(cp, manifest)
    assert findings and all(f.rule == "PT603" for f in findings)
    assert any("missing from the compiled module" in f.message
               for f in findings)
    assert any("aliases 0 bytes" in f.message for f in findings)
    # a program that donates nothing has nothing to prove
    cp = sa.compile_program(_mem_spec(jax.jit(f), (x,), None))
    assert mem.donation_findings(cp, mem.memory_manifest(cp)) == []


# -------------------------------------------------------------- PT604
def test_pt604_temp_blowup_flags_and_small_twin_passes():
    def blowup(x):
        # the (1024, 1024) intermediate (4 MiB) must MATERIALIZE as
        # sort's operand — a single temp far past the params (= x,
        # 4 KiB); sin() blocks the (x xT) x algebraic rewrite and a
        # plain elementwise chain would loop-fuse away to temp 0
        return jnp.sort(jnp.sin(jnp.outer(x, x)), axis=1).sum()

    x = jnp.ones((1024,), jnp.float32)
    cp = sa.compile_program(_mem_spec(
        jax.jit(blowup), (x,), None, mem_roles=(("params", 0, None),)))
    manifest = mem.memory_manifest(cp)
    nbytes, what = mem.largest_temp(cp.hlo)
    assert nbytes >= 1024 * 1024 * 4
    findings = mem.temp_findings(cp, manifest)
    assert [f.rule for f in findings] == ["PT604"]
    assert "single temp buffer" in findings[0].message

    def small(x):
        return (x * 2.0).sum()

    cp = sa.compile_program(_mem_spec(
        jax.jit(small), (x,), None, mem_roles=(("params", 0, None),)))
    assert mem.temp_findings(cp, mem.memory_manifest(cp)) == []


def test_largest_temp_counts_async_start_output_half_only():
    """A sync<->async collective spelling flip must not double-count
    into a false PT604: the -start result tuple carries operand AND
    output buffers, and only the output half allocates new bytes
    (the same accounting pass 4's _shape_bytes applies)."""
    sync = ("ENTRY %main (p: f32[8]) -> f32[8] {\n"
            "  %ag = f32[64]{0} all-gather(f32[8]{0} %p), dimensions={0}\n"
            "}\n")
    async_ = ("ENTRY %main (p: f32[8]) -> f32[8] {\n"
              "  %ag = (f32[8]{0}, f32[64]{0}) all-gather-start("
              "f32[8]{0} %p), dimensions={0}\n"
              "  %agd = f32[64]{0} all-gather-done(%ag)\n"
              "}\n")
    assert mem.largest_temp(sync)[0] == 64 * 4
    assert mem.largest_temp(async_)[0] == 64 * 4


# -------------------------------------------------------------- PT605
def test_pt605_manifest_must_match_profiler_accounting():
    mesh = _mesh8()

    def f(w, batch):
        return (batch @ w).sum()

    w = jax.device_put(jnp.ones((128, 16)), NamedSharding(mesh, P()))
    batch = jax.device_put(jnp.ones((8, 128)),
                           NamedSharding(mesh, P("data")))
    cp = sa.compile_program(_mem_spec(
        jax.jit(f), (w, batch), mesh,
        mem_roles=(("params", 0, None), ("acts", 1, None))))
    manifest = mem.memory_manifest(cp)
    assert manifest["param_bytes"] == 128 * 16 * 4  # replicated
    assert manifest["act_bytes"] == 8 * 128 * 4 // 8  # 1/8 shard
    assert mem.reconcile_findings(cp, manifest) == []
    # tampered manifest (= a drifted static accounting) must flag
    bad = dict(manifest)
    bad["param_bytes"] += 4
    findings = mem.reconcile_findings(cp, bad)
    assert [f.rule for f in findings] == ["PT605"]
    assert "memory_stats" in findings[0].message


# ------------------------------------------------- PT401 MEM_* family
def test_pt401_mem_artifact_shape(tmp_path):
    good = {"programs": {"zero1": {"arg_bytes": 91504,
                                   "resident_bytes": 236708}}}
    p = tmp_path / "MEM_r15.json"
    p.write_text(json.dumps(good))
    assert check_bench_file(str(p), "MEM_r15.json") == []
    # missing programs map
    p.write_text(json.dumps({"zero1": {"arg_bytes": 1}}))
    findings = check_bench_file(str(p), "MEM_r15.json")
    assert [f.rule for f in findings] == ["PT401"]
    assert "'programs'" in findings[0].message
    # non-int / negative byte counts
    p.write_text(json.dumps({"programs": {"zero1": {"arg_bytes": -1},
                                          "bad": 7}}))
    findings = check_bench_file(str(p), "MEM_r15.json")
    assert findings and all(f.rule == "PT401" for f in findings)
    assert any("non-negative int" in f.message for f in findings)
    assert any("non-empty object" in f.message for f in findings)
    # empty programs map recorded nothing
    p.write_text(json.dumps({"programs": {}}))
    findings = check_bench_file(str(p), "MEM_r15.json")
    assert [f.rule for f in findings] == ["PT401"]


def test_schema_check_scans_mem_pattern(tmp_path):
    from paddle_tpu.analysis.bench_schema import run_schema_check
    (tmp_path / "MEM_r15.json").write_text("{broken")
    findings = run_schema_check(str(tmp_path))
    assert [f.path for f in findings] == ["MEM_r15.json"]


# --------------------------------------------- PT401 health timelines
def test_pt401_health_artifact_shape(tmp_path):
    """The HEALTH_* family (training-health timelines): non-empty
    monotone step events, each with a finite numeric loss — the good
    twin validates, and each defect fires with its own message."""
    good = {"run": "bench-r16", "period": 1, "sentry_trips": 0,
            "events": [
                {"step": 0, "loss": 1.25, "lr": 0.001},
                {"step": 1, "loss": 1.19,
                 "param_stats": {"w": {"norm": 3.0}}},
            ]}
    p = tmp_path / "HEALTH_r16.json"
    p.write_text(json.dumps(good))
    assert check_bench_file(str(p), "HEALTH_r16.json") == []
    # empty events recorded nothing
    p.write_text(json.dumps({"run": "x", "period": 1, "events": []}))
    findings = check_bench_file(str(p), "HEALTH_r16.json")
    assert [f.rule for f in findings] == ["PT401"]
    assert "non-empty 'events'" in findings[0].message
    # shuffled steps, missing loss, missing run/period
    bad = {"events": [{"step": 3, "loss": 1.0}, {"step": 1}]}
    p.write_text(json.dumps(bad))
    findings = check_bench_file(str(p), "HEALTH_r16.json")
    assert findings and all(f.rule == "PT401" for f in findings)
    assert any("monotone step order" in f.message for f in findings)
    assert any("'loss'" in f.message for f in findings)
    assert any("'run'" in f.message for f in findings)
    assert any("'period'" in f.message for f in findings)
    # a NaN loss rejects via the shared finite-number walk
    p.write_text('{"run": "x", "period": 0, '
                 '"events": [{"step": 0, "loss": NaN}]}')
    findings = check_bench_file(str(p), "HEALTH_r16.json")
    assert any("non-finite" in f.message for f in findings)


def test_schema_check_scans_health_pattern(tmp_path):
    from paddle_tpu.analysis.bench_schema import run_schema_check
    (tmp_path / "HEALTH_r16.json").write_text("{broken")
    findings = run_schema_check(str(tmp_path))
    assert [f.path for f in findings] == ["HEALTH_r16.json"]


def test_json_output_carries_pass5_fields(tmp_path, capsys):
    """The --json contract grew pass5_s and mem_manifest; when pass 5
    is skipped both are null (the keys are always present so CI
    consumers need no existence checks)."""
    from paddle_tpu.analysis.__main__ import run
    rc = run(["--root", str(tmp_path), "--json", "--skip-ast",
              "--skip-locks", "--skip-jaxpr", "--skip-shard",
              "--skip-mem"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert "pass5_s" in doc and doc["pass5_s"] is None
    assert "mem_manifest" in doc and doc["mem_manifest"] is None
