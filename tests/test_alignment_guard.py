"""Padded-length alignment shims must distinguish benign feeder padding
(trimmed/zero-filled positions are masked dead) from genuinely misaligned
data, which the reference would CHECK-fail on (misaligned
``sequenceStartPositions``). The guard (`core/argument.py:check_dead`)
raises at run time through a debug callback, since masks are traced."""

import types

import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.core.argument import Argument, check_dead


def test_check_dead_passes_when_tail_is_masked_dead():
    @jax.jit
    def f(mask):
        check_dead(jnp.sum(mask[:, 2:]), "trim")
        return mask[:, :2]

    out = f(jnp.asarray([[1.0, 1.0, 0.0, 0.0]]))
    assert out.shape == (1, 2)


def test_check_dead_raises_on_live_positions():
    @jax.jit
    def f(mask):
        check_dead(jnp.sum(mask[:, 2:]), "trim")
        return mask[:, :2]

    with pytest.raises(Exception, match="live|callback"):
        jax.block_until_ready(f(jnp.ones((1, 4))))


def _expand_nested(src_subs, live_subs, total_subs):
    """Drive ExpandLayer's nested-target branch directly."""
    from paddle_tpu.core.registry import get_layer_impl

    impl = get_layer_impl("expand")
    cfg = types.SimpleNamespace(name="ex", attrs={})
    B, T, D = 1, 2, 3
    src = Argument(
        value=jnp.ones((B, src_subs, D)),
        mask=jnp.ones((B, src_subs)))
    ref_mask = jnp.zeros((B, total_subs, T)).at[:, :live_subs, :].set(1.0)
    ref = Argument(value=jnp.zeros((B, total_subs, T, D)), mask=ref_mask)

    @jax.jit
    def run():
        return impl.apply(cfg, {}, [src, ref], None)

    return jax.block_until_ready(run().value)


def test_expand_pads_dead_subs_silently():
    # source covers every LIVE sub; extra dead subs are benign padding
    v = _expand_nested(src_subs=2, live_subs=2, total_subs=4)
    assert v.shape == (1, 4, 2, 3)


def test_expand_raises_when_live_subs_would_get_zeros():
    with pytest.raises(Exception, match="live|callback"):
        _expand_nested(src_subs=2, live_subs=3, total_subs=4)
