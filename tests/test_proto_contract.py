"""Wire-format parity of the vendored proto contract.

Compiles the reference's schemas (`/root/reference/proto/*.proto`) with
protoc into a FileDescriptorSet, loads them into a *private* descriptor
pool (the default pool already holds our same-named files), and checks
that messages serialized by our gencode parse identically under the
reference schema and vice versa — the contract that makes
reference-produced configs interoperable.
"""

import pathlib
import shutil
import subprocess

import pytest
from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

REF_PROTO = pathlib.Path("/root/reference/proto")

pytestmark = pytest.mark.skipif(
    shutil.which("protoc") is None or not REF_PROTO.is_dir(),
    reason="needs protoc + the reference schemas")


@pytest.fixture(scope="module")
def ref_msgs(tmp_path_factory):
    out = tmp_path_factory.mktemp("refpb") / "ref.desc"
    protos = sorted(REF_PROTO.glob("*.proto"))
    subprocess.run(
        ["protoc", f"-I{REF_PROTO}", "-o", str(out), "--include_imports"]
        + [str(p) for p in protos], check=True)
    fds = descriptor_pb2.FileDescriptorSet()
    fds.ParseFromString(out.read_bytes())
    pool = descriptor_pool.DescriptorPool()
    for f in fds.file:
        pool.Add(f)

    def cls(name):
        return message_factory.GetMessageClass(
            pool.FindMessageTypeByName(f"paddle.{name}"))

    return cls


def _fill_model(m):
    m.type = "nn"
    lay = m.layers.add()
    lay.name = "img"
    lay.type = "data"
    lay.size = 784
    fc = m.layers.add()
    fc.name = "fc1"
    fc.type = "fc"
    fc.size = 128
    fc.active_type = "relu"
    inp = fc.inputs.add()
    inp.input_layer_name = "img"
    inp.input_parameter_name = "w1"
    p = m.parameters.add()
    p.name = "w1"
    p.size = 784 * 128
    p.initial_std = 0.05
    p.dims.extend([784, 128])
    m.input_layer_names.append("img")
    m.output_layer_names.append("fc1")


def test_model_config_cross_parse(ref_msgs):
    from paddle_tpu import proto
    ours = proto.ModelConfig()
    _fill_model(ours)
    theirs = ref_msgs("ModelConfig")()
    _fill_model(theirs)
    assert ours.SerializeToString(deterministic=True) == \
        theirs.SerializeToString(deterministic=True)
    # cross-parse: reference-schema bytes into our gencode
    back = proto.ModelConfig()
    back.ParseFromString(theirs.SerializeToString())
    assert back.layers[1].active_type == "relu"
    assert list(back.parameters[0].dims) == [784, 128]


def test_trainer_config_cross_parse(ref_msgs):
    from paddle_tpu import proto

    def fill(tc):
        tc.opt_config.batch_size = 128
        tc.opt_config.algorithm = "sgd"
        tc.opt_config.learning_rate = 0.01
        tc.opt_config.learning_method = "adam"
        tc.opt_config.adam_beta1 = 0.95
        tc.save_dir = "./out"

    ours, theirs = proto.TrainerConfig(), ref_msgs("TrainerConfig")()
    fill(ours)
    fill(theirs)
    assert ours.SerializeToString(deterministic=True) == \
        theirs.SerializeToString(deterministic=True)


def test_defaults_match_reference(ref_msgs):
    """Spot-check defaults that the config compiler relies on."""
    from paddle_tpu import proto
    ours, theirs = proto.ParameterConfig(), ref_msgs("ParameterConfig")()
    for f in ["learning_rate", "momentum", "initial_mean", "initial_std",
              "decay_rate", "initial_strategy", "initial_smart",
              "num_batches_regularization", "is_sparse", "is_static"]:
        assert getattr(ours, f) == getattr(theirs, f), f
    o2, t2 = proto.OptimizationConfig(), ref_msgs("OptimizationConfig")()
    for f in ["algorithm", "learning_rate_schedule", "learning_method",
              "average_window", "adam_beta1", "adam_beta2", "adam_epsilon",
              "gradient_clipping_threshold", "l1weight", "l2weight"]:
        assert getattr(o2, f) == getattr(t2, f), f
    lo, lt = proto.LayerConfig(), ref_msgs("LayerConfig")()
    for f in ["shared_biases", "device", "reversed", "num_neg_samples",
              "coeff", "trans_type", "moving_average_fraction", "blank",
              "seq_pool_stride", "axis"]:
        assert getattr(lo, f) == getattr(lt, f), f


def test_every_reference_field_exists(ref_msgs, tmp_path):
    """Field-by-field schema audit: every field of every reference message
    exists in ours with the same number, type, label, and default."""
    import paddle_tpu
    our_desc = tmp_path / "ours.desc"
    defs = pathlib.Path(paddle_tpu.__file__).parent / "proto" / "defs"
    subprocess.run(
        ["protoc", f"-I{defs}", "-o", str(our_desc), "--include_imports"]
        + [str(p) for p in sorted(defs.glob("*.proto"))], check=True)
    fds = descriptor_pb2.FileDescriptorSet()
    fds.ParseFromString(our_desc.read_bytes())
    our_pool = descriptor_pool.DescriptorPool()
    for f in fds.file:
        our_pool.Add(f)

    ref_set = tmp_path / "ref.desc"
    subprocess.run(
        ["protoc", f"-I{REF_PROTO}", "-o", str(ref_set), "--include_imports"]
        + [str(p) for p in sorted(REF_PROTO.glob("*.proto"))], check=True)
    ref_fds = descriptor_pb2.FileDescriptorSet()
    ref_fds.ParseFromString(ref_set.read_bytes())

    checked = 0

    def audit_message(msg, ours, scope):
        """Recursive audit so nested message/enum types stay covered if
        the schemas ever grow them (today the reference nests none)."""
        nonlocal checked
        our_fields = {fl.number: fl for fl in ours.fields}
        for fl in msg.field:
            assert fl.number in our_fields, \
                f"{scope}.{fl.name} (#{fl.number}) missing"
            o = our_fields[fl.number]
            assert o.name == fl.name, (scope, fl.name, o.name)
            assert o.type == fl.type, (scope, fl.name)
            assert o.label == fl.label, (scope, fl.name)
            if fl.HasField("default_value"):
                if o.enum_type is not None:
                    got = o.enum_type.values_by_number[
                        o.default_value].name
                else:
                    got = str(o.default_value)
                assert got in (
                    fl.default_value,
                    str(fl.default_value),
                    # bools/numbers stringify differently
                    str(fl.default_value).capitalize(),
                ) or float_eq(o.default_value, fl.default_value), \
                    (scope, fl.name, o.default_value, fl.default_value)
            checked += 1
        our_nested = {n.name: n for n in ours.nested_types}
        for nested in msg.nested_type:
            assert nested.name in our_nested, \
                f"nested message {scope}.{nested.name} missing"
            audit_message(nested, our_nested[nested.name],
                          f"{scope}.{nested.name}")
        our_enums = {e.name: e for e in ours.enum_types}
        for enum in msg.enum_type:
            assert enum.name in our_enums, \
                f"nested enum {scope}.{enum.name} missing"
            ours_vals = {v.number: v.name
                         for v in our_enums[enum.name].values}
            for v in enum.value:
                assert ours_vals.get(v.number) == v.name, \
                    (scope, enum.name, v.name, v.number)

    for f in ref_fds.file:
        # top-level enums audit too (EnumDescriptorProto at file scope)
        for enum in f.enum_type:
            ours_enum = our_pool.FindEnumTypeByName(f"paddle.{enum.name}")
            ours_vals = {v.number: v.name for v in ours_enum.values}
            for v in enum.value:
                assert ours_vals.get(v.number) == v.name, \
                    (enum.name, v.name, v.number)
        for msg in f.message_type:
            ours = our_pool.FindMessageTypeByName(f"paddle.{msg.name}")
            audit_message(msg, ours, msg.name)
    assert checked > 200  # the contract is nontrivial


def float_eq(a, b):
    try:
        return abs(float(a) - float(b)) < 1e-12
    except (TypeError, ValueError):
        return False
