"""Replica supervisor: process lifecycle as tested framework behavior.

The r14 kill-discrimination contract, driven against REAL child
processes (tiny stub replica servers — the supervisor only ever talks
HTTP + signals, so the stub exercises the identical surface the CLI's
``--job=serve`` children do, in milliseconds instead of model-warmup
seconds):

- a HUNG replica (process alive, health probes never answered) dies by
  LEASE EXPIRY: SIGTERM → grace → SIGKILL → reap → respawn;
- a CRASHED replica (process exited) is reaped and respawned
  immediately;
- a SLOW-BUT-HEARTBEATING straggler is NEVER killed — slowness is the
  router's breaker/hedge business, not the lifecycle plane's;
- dropped lease renewals (chaos site ``lease_renew``) expire a healthy
  replica's lease — and even then two live processes serving one
  replica id are impossible (the reap gates every respawn);
- spawns ride the ``supervisor_spawn`` chaos site: a dropped spawn
  leaves the slot down and the next sweep retries.

Plus the RoleLease election/fencing unit contract and the remote-drain
satellite: ``POST /admin/drain`` on the real single-replica server,
and ``HTTPTransport`` draining Popen-less replicas through it.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import threading
import time
import urllib.request

import pytest

from paddle_tpu.dist.master import (FileStore, InMemStore, LeaseTable,
                                    RoleLease)
from paddle_tpu.serving.router import HTTPTransport
from paddle_tpu.serving.supervisor import ReplicaSupervisor, free_port
from paddle_tpu.testing import chaos

# --------------------------------------------------------------- stub
# A stand-in replica process: answers the same /healthz + /admin/drain
# surface a real single-replica server does, with control endpoints to
# make it hang (stop answering health), crash (exit), or slow down.
STUB = textwrap.dedent("""
    import json, os, sys, threading, time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    state = {"hang": False, "slow_s": 0.0, "draining": False}

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        def log_message(self, *a): pass
        def _send(self, code, body):
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        def do_GET(self):
            if self.path == "/healthz":
                if state["hang"]:
                    time.sleep(3600)
                if state["slow_s"]:
                    time.sleep(state["slow_s"])
                self._send(200, {"status": "ok", "live": True,
                                 "ready": not state["draining"],
                                 "draining": state["draining"],
                                 "queue_depth": 0, "inflight": 0,
                                 "backlog_ms": 1.0,
                                 "model_version": "stub",
                                 "pid": os.getpid()})
            else:
                self._send(404, {})
        def do_POST(self):
            if self.path == "/admin/drain":
                state["draining"] = True
                self._send(200, {"draining": True})
            elif self.path == "/admin/hang":
                state["hang"] = True
                self._send(200, {})
            elif self.path == "/admin/slow":
                state["slow_s"] = 0.3
                self._send(200, {})
            elif self.path == "/admin/die":
                self._send(200, {})
                os._exit(7)
            else:
                self._send(404, {})

    srv = ThreadingHTTPServer(("127.0.0.1", int(sys.argv[1])), H)
    srv.daemon_threads = True
    srv.serve_forever()
""")


def _stub_spawn_factory(tmpdir):
    path = os.path.join(tmpdir, "stub_replica.py")
    with open(path, "w") as f:
        f.write(STUB)

    def spawn(replica_id):
        port = free_port()
        proc = subprocess.Popen([sys.executable, path, str(port)])
        return proc, "127.0.0.1", port

    return spawn


def _post(transport, path):
    url = f"http://{transport.host}:{transport.port}{path}"
    with urllib.request.urlopen(
            urllib.request.Request(url, data=b"{}", method="POST"),
            timeout=5.0) as r:
        return json.loads(r.read() or b"{}")


def _drive(sup, until, timeout=20.0, settle=0.05):
    """Deterministically drive supervision sweeps until ``until()``."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sup.poll_once()
        if until():
            return True
        time.sleep(settle)
    return until()


@pytest.fixture
def stub_spawn(tmp_path):
    return _stub_spawn_factory(str(tmp_path))


def _events(sup, kind, rid=None):
    return [e for e in sup.events
            if e[1] == kind and (rid is None or e[2] == rid)]


# ------------------------------------------------------------- matrix
def test_lease_expiry_matrix_hung_crashed_straggler(stub_spawn):
    """The kill-discrimination matrix: hung → lease-expiry kill +
    respawn; crashed → reap + respawn; slow-but-heartbeating → never
    killed (same pid end to end)."""
    sup = ReplicaSupervisor(stub_spawn, replicas=3,
                            lease_timeout_s=1.0, grace_s=0.5,
                            healthz_timeout_s=0.6)
    try:
        sup.start(wait_ready_s=20.0)
        pids0 = {r["id"]: r["pid"] for r in sup.snapshot()["replicas"]}
        assert all(pids0.values())
        with sup._lock:
            reps = dict(sup._replicas)
        _post(reps["r2"].transport, "/admin/slow")   # straggler
        _post(reps["r0"].transport, "/admin/hang")   # hung
        _post(reps["r1"].transport, "/admin/die")    # crashed
        # wait for the RE-spawns (the initial start() spawn is event
        # one, so the bar is two per affected replica)
        assert _drive(sup, lambda:
                      len(_events(sup, "spawned", "r0")) >= 2
                      and len(_events(sup, "spawned", "r1")) >= 2)
        # r0 died by LEASE EXPIRY → escalate → respawn with a new pid
        assert _events(sup, "lease_expired", "r0")
        assert _events(sup, "killed", "r0")
        # r1 crashed on its own: reaped + respawned, never signalled
        assert _events(sup, "spawned", "r1")
        assert not _events(sup, "killed", "r1")
        assert not _events(sup, "lease_expired", "r1")
        # the straggler answered (slowly) every probe: untouched
        assert not _events(sup, "killed", "r2")
        assert not _events(sup, "lease_expired", "r2")
        pids1 = {r["id"]: r["pid"] for r in sup.snapshot()["replicas"]}
        assert pids1["r2"] == pids0["r2"]
        assert pids1["r0"] not in (None, pids0["r0"])
        assert pids1["r1"] not in (None, pids0["r1"])
        # the killed/old pids are truly gone (reaped, not zombied-live)
        for old in (pids0["r0"], pids0["r1"]):
            with pytest.raises(ProcessLookupError):
                os.kill(old, 0)
    finally:
        sup.shutdown(drain=False)


@pytest.mark.chaos
def test_dropped_lease_renewals_cannot_double_spawn(stub_spawn):
    """Seeded chaos drops EVERY lease renewal after the first: the
    (healthy) replica's lease expires and the supervisor kills +
    respawns it — but at no point do two live processes serve the same
    replica id: every ``spawned`` event is preceded by the previous
    process's reap, and only the final pid is alive afterwards."""
    plan = chaos.FaultPlan(seed=23, faults=[
        {"type": "drop", "site": "lease_renew", "after": 1,
         "count": 10_000}])
    sup = ReplicaSupervisor(stub_spawn, replicas=1,
                            lease_timeout_s=0.8, grace_s=0.4,
                            healthz_timeout_s=0.5)
    try:
        with chaos.chaos_plan(plan):
            sup.start(wait_ready_s=20.0)
            assert _drive(sup, lambda: len(_events(sup, "spawned",
                                                   "r0")) >= 2,
                          settle=0.2)
        assert plan.hits("lease_renew") > 1
        assert _events(sup, "lease_renew_lost", "r0")
        assert _events(sup, "lease_expired", "r0")
        spawned = _events(sup, "spawned", "r0")
        killed_or_crashed = (_events(sup, "killed", "r0")
                             + _events(sup, "crashed", "r0"))
        # between consecutive spawns there is always a completed reap
        for a, b in zip(spawned, spawned[1:]):
            assert any(a[0] < e[0] < b[0] for e in killed_or_crashed), \
                "a respawn fired without reaping the previous process"
        pids = [e[3]["pid"] for e in spawned]
        live = [p for p in pids if _alive(p)]
        assert live == [pids[-1]], (
            f"multiple live processes for one replica id: {live}")
    finally:
        sup.shutdown(drain=False)


def _alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


def test_spawn_drop_leaves_slot_down_and_retries(stub_spawn):
    """An injected ``supervisor_spawn`` drop fails the spawn; the slot
    stays down and the next sweep retries successfully."""
    plan = chaos.FaultPlan(seed=5, faults=[
        {"type": "drop", "site": "supervisor_spawn", "at": 1}])
    sup = ReplicaSupervisor(stub_spawn, replicas=1,
                            lease_timeout_s=2.0)
    try:
        with chaos.chaos_plan(plan):
            sup.start()  # first spawn dropped
            assert _events(sup, "spawn_failed", "r0")
            assert sup.snapshot()["replicas"][0]["pid"] is None
            sup.poll_once()  # retry path: slot down → respawn
        assert _events(sup, "spawned", "r0")
        assert sup.wait_ready(20.0)
    finally:
        sup.shutdown(drain=False)


# ------------------------------------------------------- remote drain
def test_admin_drain_and_popen_less_http_transport(serving_engine_http):
    """The remote-drain satellite against the REAL single-replica
    server: ``POST /admin/drain`` closes admission (429 shutting_down),
    and a Popen-LESS HTTPTransport drains through the endpoint — the
    r13 'drain must be driven out of band' warning path is gone."""
    host, port, engine = serving_engine_http
    t = HTTPTransport(host, port)  # no proc handle on purpose
    assert t.healthz()["ready"]
    t.begin_drain()
    assert engine.draining
    h = t.healthz()
    assert h["draining"] and not h["ready"]
    assert "inflight" in h
    t.drain_wait(timeout=10.0)  # queue is empty → returns promptly
    from paddle_tpu.serving import ServingClient
    from paddle_tpu.serving.errors import Overloaded
    with pytest.raises(Overloaded):  # admission is closed: 429
        ServingClient(host, port).score([[0.1] * 8, 0])


@pytest.fixture(scope="module")
def serving_engine_http():
    """One real tiny engine + HTTP frontend (module-scoped: the 1-core
    host cannot afford per-test warmup)."""
    import numpy as np  # noqa: F401
    import jax
    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network
    from paddle_tpu.data import dense_vector, integer_value
    from paddle_tpu.serving import ServingEngine, ServingPredictor
    from paddle_tpu.serving.server import make_server

    dsl.reset()
    x = dsl.data(name="x", size=8)
    lab = dsl.data(name="label", size=4)
    out = dsl.fc(input=x, size=4, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    feeding = {"x": dense_vector(8), "label": integer_value(4)}
    pred = ServingPredictor(graph, params, ["out"], feeding,
                            batch_buckets=[1, 2])
    engine = ServingEngine(pred, max_batch=2,
                           batch_timeout_ms=1.0).start(warmup=True)
    server = make_server(engine, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    host, port = server.server_address
    yield host, port, engine
    server.shutdown()
    engine.shutdown()


# ---------------------------------------------------------- RoleLease
def test_role_lease_acquire_renew_expire_and_epoch_fence(tmp_path):
    """The election/fencing contract: one live holder at a time; a
    stale lease is taken with a BUMPED epoch; the old holder's next
    renew sees the foreign epoch, fails, and self-fences."""
    store = FileStore(str(tmp_path / "role.json"))
    a = RoleLease(store, "A", ttl_s=0.3, settle_s=0.0)
    b = RoleLease(store, "B", ttl_s=0.3, settle_s=0.0)
    assert a.try_acquire() and a.valid() and a.epoch == 1
    assert not b.try_acquire()  # live foreign holder
    assert a.renew()
    time.sleep(0.35)  # A stops renewing: lease goes stale
    assert not a.valid()
    assert b.try_acquire() and b.epoch == 2
    # the zombie's renew is refused by the epoch guard, permanently
    assert not a.renew() and not a.valid()
    assert b.renew() and b.valid()
    # clean release → immediate takeover, no ttl wait, epoch still grows
    b.release()
    assert not b.valid()
    assert a.try_acquire() and a.epoch == 3


def test_role_lease_renew_rides_the_lease_renew_chaos_site():
    """A dropped renewal (`lease_renew` drop) is a LOST message: the
    holder keeps its validity only until ttl, then self-fences."""
    lease = RoleLease(InMemStore(), "A", ttl_s=0.25, settle_s=0.0)
    assert lease.try_acquire()
    plan = chaos.FaultPlan(seed=3, faults=[
        {"type": "drop", "site": "lease_renew"}])
    with chaos.chaos_plan(plan):
        with pytest.raises(ConnectionError):
            lease.renew()
    assert plan.hits("lease_renew") == 1
    assert lease.valid()  # validity persists until the ttl runs out...
    time.sleep(0.3)
    assert not lease.valid()  # ...then the holder is fenced


def test_lease_table_reports_each_expiry_once():
    lt = LeaseTable(0.1)
    lt.renew("x")
    lt.renew("y")
    time.sleep(0.15)
    lt.renew("y")
    assert lt.expired() == ["x"]
    assert lt.expired() == []  # x reported exactly once
    assert "y" in lt and "x" not in lt
