"""ZeRO-1 sharded optimizer update: parity, coverage closure, memory,
and cross-mode checkpoint resume.

The reference distributes the update across pservers so no node holds the
full optimizer state (``ParameterServer2.cpp:362``); the TPU port's
equivalent is the data-axis partition in ``optim/zero1.py``. The contract
under test: the sharded update is BIT-EXACT vs the replicated path on the
8-device CPU mesh (the update math is elementwise per parameter), per-
device slot bytes drop ~N×, and checkpoints cross sharded<->replicated
modes in both directions.

``test_zero1_registry_fully_covered`` is the closure guard in the
``test_layer_grad_matrix`` style: registering a new optimizer in
``create_optimizer`` without a parity case here fails the suite, so new
optimizers cannot silently miss the sharded path.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.config import dsl
from paddle_tpu.data import DataFeeder, dense_vector, integer_value
from paddle_tpu.dist.checkpoint import Checkpointer
from paddle_tpu.optim import Adam, Momentum, Zero1Updater, create_optimizer
from paddle_tpu.optim.optimizers import _BY_NAME
from paddle_tpu.parallel import create_mesh
from paddle_tpu.trainer import SGD
from paddle_tpu.utils.profiler import memory_stats


# ----------------------------------------------------- the parity matrix
# optimizer-registry name -> constructor kwargs exercising that
# optimizer's distinctive knobs (clipping, momentum, decay...) so the
# sharded path is checked where rounding could actually diverge.
ZERO1_PARITY_CASES = {
    "momentum": dict(learning_rate=0.1, momentum=0.9,
                     gradient_clipping_threshold=0.2),
    "sgd": dict(learning_rate=0.05, l2_rate=1e-3),
    "adagrad": dict(learning_rate=0.1, momentum=0.5, l1_rate=1e-3),
    "adadelta": dict(learning_rate=0.5, rou=0.9),
    "rmsprop": dict(learning_rate=0.05, rou=0.9, momentum=0.3),
    "decayed_adagrad": dict(learning_rate=0.1, rou=0.9),
    "adam": dict(learning_rate=0.01, l2_rate=1e-3,
                 gradient_clipping_threshold=0.3),
    "adamax": dict(learning_rate=0.01, beta1=0.8),
}


def test_zero1_registry_fully_covered():
    """Closure: every optimizer create_optimizer can build has a ZeRO-1
    parity case (and no stale cases name unknown optimizers)."""
    missing = sorted(set(_BY_NAME) - set(ZERO1_PARITY_CASES))
    assert not missing, (
        f"optimizers {missing} are registered in create_optimizer but "
        "have no ZERO1_PARITY_CASES entry — add one so the sharded "
        "update path is proven bit-exact for them")
    stale = sorted(set(ZERO1_PARITY_CASES) - set(_BY_NAME))
    assert not stale, f"parity cases for unregistered optimizers: {stale}"


@pytest.fixture(scope="module")
def mesh8():
    return create_mesh(n_data=8)


@pytest.mark.parametrize("name", sorted(ZERO1_PARITY_CASES))
def test_zero1_update_bit_exact(name, mesh8):
    """Three updates on awkward (padding-requiring) shapes: params AND
    gathered slots must equal the replicated path's bitwise."""
    opt = create_optimizer(name, **ZERO1_PARITY_CASES[name])
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(13, 7).astype(np.float32)),
              "b": jnp.asarray(rng.randn(5).astype(np.float32))}
    z = Zero1Updater(opt, mesh8, params)
    s_rep = opt.init(params)
    s_z = z.convert_state(opt.init(params))
    p_rep, p_z = dict(params), dict(params)
    for _ in range(3):
        g = {k: jnp.asarray(rng.randn(*v.shape).astype(np.float32))
             for k, v in params.items()}
        p_rep, s_rep = jax.jit(opt.update)(g, s_rep, p_rep)
        p_z, s_z = jax.jit(z.update)(g, s_z, p_z)
    for k in params:
        assert np.array_equal(np.asarray(p_rep[k]), np.asarray(p_z[k])), (
            f"{name}: param {k} diverged from the replicated update")
    gathered = z.gather_opt_state(s_z)
    for k, slots in s_rep["slots"].items():
        for slot, v in slots.items():
            assert np.array_equal(
                np.asarray(v), np.asarray(gathered["slots"][k][slot])), (
                f"{name}: slot {k}/{slot} diverged")


# ------------------------------------------------------------ end to end
def _model():
    dsl.reset()
    x = dsl.data(name="x", size=16)
    lab = dsl.data(name="label", size=4)
    h = dsl.fc(input=x, size=32, act="relu", name="h")
    out = dsl.fc(input=h, size=4, act="softmax", name="out")
    return dsl.classification_cost(input=out, label=lab)


def _emb_model(vocab=50):
    dsl.reset()
    w = dsl.data(name="words", size=vocab)
    lab = dsl.data(name="label", size=4)
    e = dsl.embedding(input=w, size=16, vocab_size=vocab, name="emb")
    pooled = dsl.pooling(input=e, pooling_type="avg", name="pool")
    out = dsl.fc(input=pooled, size=4, act="softmax", name="out")
    return dsl.classification_cost(input=out, label=lab)


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 16).astype(np.float32)
    y = rng.randint(0, 4, n)
    return [(x[i], int(y[i])) for i in range(n)]


def _feeder():
    return DataFeeder({"x": dense_vector(16), "label": integer_value(4)})


def _train(data, mesh, optimizer, zero1, passes=2, checkpointer=None):
    tr = SGD(cost=_model(), update_equation=optimizer, mesh=mesh, seed=7)

    def reader():
        yield data

    tr.train(reader, feeder=_feeder(), num_passes=passes, zero1=zero1,
             checkpointer=checkpointer)
    return tr


@pytest.mark.parametrize("opt_name", ["momentum", "adam"])
def test_trainer_zero1_bit_exact(opt_name, mesh8):
    """The acceptance claim: a trained model under zero1 equals the
    replicated run bitwise on the 8-device CPU mesh."""
    kw = ZERO1_PARITY_CASES[opt_name]
    data = _data()
    t_rep = _train(data, mesh8, create_optimizer(opt_name, **kw), False)
    t_z = _train(data, mesh8, create_optimizer(opt_name, **kw), True)
    assert t_z._zero1 is not None
    for k in t_rep.params:
        assert np.array_equal(np.asarray(t_rep.params[k]),
                              np.asarray(t_z.params[k])), k


def test_zero1_slot_bytes_reduced_adam(mesh8):
    """Per-device optimizer-slot bytes drop ~8× for Adam (2 slots) on the
    8-way data axis; parameters stay replicated (full bytes)."""
    data = _data()
    t_rep = _train(data, mesh8, Adam(learning_rate=1e-3), False, passes=1)
    t_z = _train(data, mesh8, Adam(learning_rate=1e-3), True, passes=1)
    m_rep = memory_stats(t_rep.params, t_rep.opt_state)
    m_z = memory_stats(t_z.params, t_z.opt_state)
    ratio = m_rep["slot_bytes_per_device"] / m_z["slot_bytes_per_device"]
    assert ratio > 6.0, f"slot bytes only reduced {ratio:.2f}x (want ~8x)"
    assert m_rep["param_bytes_per_device"] == m_z["param_bytes_per_device"]


def test_zero1_toggle_off_restores_replicated_update(mesh8):
    """train(zero1=False) after a zero1 run must actually disable it
    (code-review finding: a one-way toggle mislabels A/B measurements):
    slots reshard to full shapes and training continues bit-identically
    to an all-replicated run. zero1=None keeps the current mode."""
    data = _data()
    t_rep = _train(data, mesh8, Adam(learning_rate=1e-2), False, passes=3)

    tr = SGD(cost=_model(), mesh=mesh8, seed=7,
             update_equation=Adam(learning_rate=1e-2))

    def reader():
        yield data

    tr.train(reader, feeder=_feeder(), num_passes=1, zero1=True)
    assert tr._zero1 is not None
    tr.train(reader, feeder=_feeder(), num_passes=1)  # None: keep zero1
    assert tr._zero1 is not None
    tr.train(reader, feeder=_feeder(), num_passes=1, zero1=False)
    assert tr._zero1 is None
    shapes = {n: tuple(v.shape) for n, v in
              tr.opt_state["slots"]["_h.w0"].items()}
    assert shapes == {n: tuple(v.shape) for n, v in
                      t_rep.opt_state["slots"]["_h.w0"].items()}
    for k in t_rep.params:
        assert np.array_equal(np.asarray(t_rep.params[k]),
                              np.asarray(tr.params[k])), k


def test_zero1_falls_back_without_data_axis():
    """No mesh (or a 1-device data axis): train(zero1=True) warns and
    keeps the replicated update — same results, no sharded state."""
    data = _data()
    t_plain = _train(data, None, Momentum(learning_rate=0.1, momentum=0.9),
                     False)
    t_req = _train(data, None, Momentum(learning_rate=0.1, momentum=0.9),
                   True)
    assert t_req._zero1 is None
    for k in t_plain.params:
        np.testing.assert_allclose(np.asarray(t_plain.params[k]),
                                   np.asarray(t_req.params[k]),
                                   rtol=0, atol=0, err_msg=k)


def test_zero1_with_sparse_embedding_matches_replicated(mesh8):
    """A model with a sparse_grad table under Momentum: the table takes
    the excluded (replicated lazy) path, dense params shard — the mixed
    update still matches the all-replicated run bitwise."""
    rng = np.random.RandomState(3)
    data = [(list(rng.randint(0, 50, size=8)), int(rng.randint(0, 4)))
            for _ in range(32)]
    from paddle_tpu.data import integer_value_sequence

    def run(zero1):
        tr = SGD(cost=_emb_model(), mesh=mesh8, seed=5,
                 update_equation=Momentum(learning_rate=0.1, momentum=0.9))
        feeder = DataFeeder({"words": integer_value_sequence(50),
                             "label": integer_value(4)}, pad_multiple=8)

        def reader():
            yield data

        tr.train(reader, feeder=feeder, num_passes=2, zero1=zero1)
        return tr

    t_rep, t_z = run(False), run(True)
    assert t_z._zero1 is not None
    sparse_names = {n for n, s in t_z.network.param_specs.items()
                    if getattr(s, "sparse_grad", False)}
    assert sparse_names and not (sparse_names & set(t_z._zero1.plan)), \
        "sparse lazy-path tables must be excluded from the ZeRO-1 plan"
    for k in t_rep.params:
        assert np.array_equal(np.asarray(t_rep.params[k]),
                              np.asarray(t_z.params[k])), k


# ------------------------------------------------- checkpoints cross modes
def _ck_reader():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 16).astype(np.float32)
    Y = np.argmax(X[:, :4], axis=1)

    def reader():
        for i in range(0, 64, 16):
            yield [(X[j], int(Y[j])) for j in range(i, i + 16)]

    return reader


@pytest.mark.parametrize("first_zero1,second_zero1",
                         [(True, False), (False, True), (True, True)])
def test_checkpoint_resume_crosses_modes(tmp_path, mesh8, first_zero1,
                                         second_zero1):
    """save -> load -> resume with the update mode flipped: checkpoints
    store gathered (full-shape) slots, so a zero1 run restores into a
    replicated one and vice versa, matching the uninterrupted run."""
    reader = _ck_reader()

    def make():
        return SGD(cost=_model(), mesh=mesh8, seed=7,
                   update_equation=Adam(learning_rate=1e-2))

    t_full = make()
    t_full.train(reader, feeder=_feeder(), num_passes=4, zero1=second_zero1)

    ckdir = str(tmp_path / f"ck_{first_zero1}_{second_zero1}")
    t_a = make()
    t_a.train(reader, feeder=_feeder(), num_passes=2, zero1=first_zero1,
              checkpointer=Checkpointer(ckdir, saving_period=1))
    t_b = make()  # fresh process state
    t_b.train(reader, feeder=_feeder(), num_passes=4, zero1=second_zero1,
              checkpointer=Checkpointer(ckdir, saving_period=1))

    for k in t_full.params:
        np.testing.assert_allclose(np.asarray(t_full.params[k]),
                                   np.asarray(t_b.params[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)


def test_zero1_checkpoint_format_matches_replicated(tmp_path, mesh8):
    """The on-disk key set and array shapes are identical whichever mode
    saved — the format-compatibility contract of _opt_state_for_save."""
    from paddle_tpu.trainer.checkpoint import load_params, save_params
    data = _data()
    t_rep = _train(data, mesh8, Adam(learning_rate=1e-3), False, passes=1)
    t_z = _train(data, mesh8, Adam(learning_rate=1e-3), True, passes=1)
    save_params(str(tmp_path / "rep"), t_rep.params,
                t_rep._opt_state_for_save)
    save_params(str(tmp_path / "z"), t_z.params, t_z._opt_state_for_save)
    _, rep_flat = load_params(str(tmp_path / "rep"))
    _, z_flat = load_params(str(tmp_path / "z"))
    assert sorted(rep_flat) == sorted(z_flat)
    for k in rep_flat:
        assert rep_flat[k].shape == z_flat[k].shape, k
