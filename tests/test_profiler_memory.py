"""``utils/profiler`` memory accounting: the documented
``memory_stats`` return schema (graftlint PT605 reconciles the
compiled per-device manifest against exactly this accounting), the
activations / temp-estimator hooks, and ``device_peak_bytes``'s
None-means-unmeasured contract on CPU.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import create_mesh
from paddle_tpu.utils.profiler import (device_peak_bytes, memory_stats,
                                       tree_device_bytes)


def test_device_peak_bytes_is_none_not_zero_on_cpu():
    """XLA:CPU exposes no peak-allocation counter: the result is None
    ("unmeasured"), NEVER 0 — a caller that treated it as 0 would let
    any admission budget pass on an off-tunnel dryrun. memory_stats
    omits the key entirely in that case."""
    peak = device_peak_bytes()
    assert peak is None or (isinstance(peak, int) and peak > 0)
    stats = memory_stats({"w": jnp.ones((4, 4))})
    if peak is None:  # the CPU container path — always taken in CI
        assert "device_peak_bytes" not in stats
        assert stats.get("device_peak_bytes") != 0


def test_memory_stats_schema_and_hooks():
    """The documented return schema: params always, slots/avg from
    opt_state, act bytes from the activations hook, temp bytes from
    the estimator hook (silent when the estimator reports None)."""
    mesh = create_mesh(n_data=8)
    params = {"w": jax.device_put(jnp.ones((128, 16)),
                                  NamedSharding(mesh, P()))}
    opt = {"slots": {"w": {"m": jax.device_put(
        jnp.ones((128, 16)), NamedSharding(mesh, P("data", None)))}},
        "avg": {"w": jnp.ones((128, 16))}}
    batch = {"x": jax.device_put(jnp.ones((8, 128)),
                                 NamedSharding(mesh, P("data", None)))}
    stats = memory_stats(params, opt, activations=batch,
                         temp_estimator=lambda: 12345)
    assert stats["param_bytes_per_device"] == 128 * 16 * 4  # replicated
    assert stats["slot_bytes_per_device"] == 128 * 16 * 4 // 8  # 1/N
    assert stats["avg_bytes_per_device"] == 128 * 16 * 4
    assert stats["act_bytes_per_device"] == 8 * 128 * 4 // 8
    assert stats["temp_bytes_per_device"] == 12345
    # hooks absent -> keys absent (schema is explicit about presence)
    bare = memory_stats(params)
    assert set(bare) <= {"param_bytes_per_device", "device_peak_bytes"}
    # an estimator that cannot measure reports None -> key omitted,
    # same None-not-0 discipline as device_peak_bytes
    stats = memory_stats(params, temp_estimator=lambda: None)
    assert "temp_bytes_per_device" not in stats


def test_memory_stats_temp_estimator_accepts_compiled_executable():
    """The documented estimator shape: lambda over a compiled
    executable's memory_analysis() — the pass-5 manifest's temp figure
    and the profiler's then agree by construction."""
    compiled = jax.jit(lambda x: jnp.sort(x)).lower(
        jnp.ones((256,))).compile()
    stats = memory_stats(
        {}, temp_estimator=lambda: compiled.memory_analysis()
        .temp_size_in_bytes)
    assert stats["temp_bytes_per_device"] == int(
        compiled.memory_analysis().temp_size_in_bytes)


def test_tree_device_bytes_counts_shard_not_global():
    mesh = create_mesh(n_data=8)
    sharded = jax.device_put(jnp.ones((64, 4)),
                             NamedSharding(mesh, P("data", None)))
    assert tree_device_bytes([sharded]) == 64 * 4 * 4 // 8


def test_memory_stats_reports_fsdp_packed_param_bytes():
    """FSDP param accounting needs no special case: the packed (N,
    chunk) leaves carry their P(fsdp) sharding, so memory_stats reads
    the 1/N per-device bytes straight from the REAL shardings — the
    figure --show_step_breakdown logs and PT605 reconciles against
    the compiled fsdp_train manifest."""
    mesh = create_mesh(n_fsdp=8)
    packed = jax.device_put(jnp.ones((8, 16)),
                            NamedSharding(mesh, P("fsdp", None)))
    stats = memory_stats({"w": packed})
    assert stats["param_bytes_per_device"] == 8 * 16 * 4 // 8


def test_memory_stats_reports_gathered_buffer_peak():
    """The r18 overlap plane: memory_stats surfaces the TRANSIENT
    gathered-buffer peak the fsdp updater computes (two layers live
    under double-buffering, one under the sync spelling) as its own
    key — it is temp memory, not resident params, so it must not fold
    into param_bytes_per_device."""
    stats = memory_stats({}, gather_peak=4096)
    assert stats["gathered_peak_bytes_per_device"] == 4096
    assert "gathered_peak_bytes_per_device" not in memory_stats({})
    # and the human-readable status line renders it like any other
    # *_bytes_per_device figure
    from paddle_tpu.utils.profiler import memory_status
    assert "gathered_peak" in memory_status({}, gather_peak=4096)


def test_fsdp_overlap_stats_exposed_comm_split():
    from paddle_tpu.utils.profiler import fsdp_overlap_stats

    sync = fsdp_overlap_stats(6, False)
    assert sync["fsdp_exposed_collectives"] == 12  # every gather+reduce
    assert sync["fsdp_exposed_comm_frac"] == 1.0
    over = fsdp_overlap_stats(6, True)
    assert over["fsdp_exposed_collectives"] == 2  # first gather+last reduce
    assert abs(over["fsdp_exposed_comm_frac"] - 2 / 12) < 1e-12
    assert fsdp_overlap_stats(0, True)["fsdp_exposed_collectives"] == 0


def test_gather_peak_is_adjacent_pair_under_overlap():
    """FsdpUpdater.gather_peak_bytes: largest single gathered layer
    under the sync spelling, largest ADJACENT PAIR in prefetch-schedule
    order under the overlap chain (exactly two buffers ever live)."""
    from paddle_tpu.optim.zero1 import FsdpUpdater, overlap_spelling
    from paddle_tpu.optim import Adam

    mesh = create_mesh(n_fsdp=8)
    params = {"a": jnp.ones((8, 16)), "b": jnp.ones((24, 16)),
              "c": jnp.ones((16, 16))}
    upd = FsdpUpdater(Adam(learning_rate=0.1), mesh, params)
    assert len(upd.plan) == 3
    sizes = {n: 8 * upd.plan[n][2] * 4 for n in upd.plan}
    order = upd.schedule
    with overlap_spelling("off"):
        assert upd.gather_peak_bytes() == max(sizes.values())
    with overlap_spelling("force"):
        want = max(sizes[a] + sizes[b]
                   for a, b in zip(order, order[1:]))
        assert upd.gather_peak_bytes() == want
