"""Replay-log format + streaming-master units (the r20 online loop's
serving→training edge, `paddle_tpu/online/`):

- PTRL1 segments: append/seal round trip, whole-file validation (any
  torn byte fails the WHOLE segment, never a partial batch), quarantine
  + skip, orphaned-tail recovery after a writer crash.
- Chaos sites ``replay_append`` / ``replay_tail``: a dropped append is
  a row that never reaches the log; a corrupted record/segment drives
  the quarantine path deterministically.
- The master's streaming pass: ``extend_dataset`` over an open stream
  dedupes by chunk value, ``get_task`` answers "wait" (not a pass roll)
  while the stream is open, and the stream flag + grown task list
  survive a FileStore recovery.
- The tailer end to end: sealed segments -> ledger tasks -> re-batched
  rows, exactly-once committed.
"""

import json
import os
import zlib

import pytest

from paddle_tpu.dist.master import FileStore, MasterService
from paddle_tpu.online.replay import (MAGIC, ReplayCorrupt, ReplayWriter,
                                      load_segment, parse_segment,
                                      quarantine, scan_segments,
                                      segment_name)
from paddle_tpu.online.tailer import ReplayTailer
from paddle_tpu.testing.chaos import ChaosDropped, FaultPlan, chaos_plan


def _rows(n, start=0):
    return [[[start + i, start + i + 1], (start + i) % 2]
            for i in range(n)]


# ------------------------------------------------------------ format
def test_append_seal_roundtrip_and_scan(tmp_path):
    w = ReplayWriter(str(tmp_path), segment_records=3,
                     schema=["words", "label"])
    for r in _rows(7):
        w.append(r)
    # 7 rows at 3/segment: two sealed, one open tail of 1
    assert w.segments_sealed == 2 and w.records_total == 7
    sealed = scan_segments(str(tmp_path))
    assert [os.path.basename(p) for p in sealed] == [
        segment_name(0), segment_name(1)]
    hdr, rows = parse_segment(sealed[0])
    assert hdr["schema"] == ["words", "label"] and hdr["seq"] == 0
    assert rows == _rows(3)
    _, rows1 = parse_segment(sealed[1])
    assert rows1 == _rows(3, start=3)
    # the open tail is invisible until sealed
    w.seal()
    sealed = scan_segments(str(tmp_path))
    assert len(sealed) == 3
    _, rows2 = parse_segment(sealed[2])
    assert rows2 == _rows(1, start=6)
    # sealing with nothing open is a no-op, not an empty segment
    w.seal()
    assert len(scan_segments(str(tmp_path))) == 3


def test_whole_segment_validation_never_partial(tmp_path):
    w = ReplayWriter(str(tmp_path), segment_records=4)
    for r in _rows(4):
        w.append(r)
    (path,) = scan_segments(str(tmp_path))
    raw = open(path, "rb").read()

    # flip a byte in the LAST record's payload: the earlier, intact
    # records must NOT surface — all-or-nothing
    torn = bytearray(raw)
    torn[-2] ^= 0xFF
    open(path, "wb").write(bytes(torn))
    with pytest.raises(ReplayCorrupt, match="CRC"):
        parse_segment(path)

    # truncation mid-record: torn, not partial
    open(path, "wb").write(raw[:-3])
    with pytest.raises(ReplayCorrupt, match="torn record"):
        parse_segment(path)

    # bad magic
    open(path, "wb").write(b"NOPE" + raw[4:])
    with pytest.raises(ReplayCorrupt, match="magic"):
        parse_segment(path)

    # intact round trip still parses (control)
    open(path, "wb").write(raw)
    _, rows = parse_segment(path)
    assert rows == _rows(4)


def test_load_segment_quarantines_and_skips(tmp_path):
    w = ReplayWriter(str(tmp_path), segment_records=2)
    for r in _rows(2):
        w.append(r)
    (path,) = scan_segments(str(tmp_path))
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    # corruption answers quarantine + NO rows, never an exception
    assert load_segment(path) == []
    assert not os.path.exists(path)
    assert os.path.exists(path + ".bad")
    # the quarantined name is invisible to the scanner forever
    assert scan_segments(str(tmp_path)) == []
    # a later redispatch of the same task finds the file gone: same
    # skip outcome, no crash
    assert load_segment(path) == []


def test_orphaned_open_tail_recovery(tmp_path):
    w1 = ReplayWriter(str(tmp_path), segment_records=10)
    for r in _rows(4):
        w1.append(r)
    # crash: the writer dies without seal() — the .open tail remains
    w1._file.flush()
    open_name = segment_name(0, sealed=False)
    assert os.path.exists(tmp_path / open_name)

    w2 = ReplayWriter(str(tmp_path), segment_records=10)
    # the unsealed tail was orphaned (at-most-once before the seal
    # boundary), numbering continues past every name ever used
    assert os.path.exists(str(tmp_path / open_name) + ".orphan")
    assert not os.path.exists(tmp_path / open_name)
    w2.append(_rows(1)[0])
    w2.seal()
    assert [os.path.basename(p)
            for p in scan_segments(str(tmp_path))] == [segment_name(1)]


# ------------------------------------------------------- chaos sites
@pytest.mark.chaos
def test_chaos_replay_append_drop_loses_exactly_that_row(tmp_path):
    w = ReplayWriter(str(tmp_path), segment_records=3)
    plan = FaultPlan(seed=0, faults=[
        {"type": "drop", "site": "replay_append", "at": 2}])
    with chaos_plan(plan):
        w.append(_rows(1)[0])
        with pytest.raises(ChaosDropped):
            w.append([[99, 99], 1])  # the dropped append
        w.append(_rows(1, start=5)[0])
        w.append(_rows(1, start=6)[0])
    assert plan.hits("replay_append") == 4
    (path,) = scan_segments(str(tmp_path))
    _, rows = parse_segment(path)
    # the dropped row is NOT in the log; its neighbors are
    assert rows == [_rows(1)[0], _rows(1, start=5)[0],
                    _rows(1, start=6)[0]]
    # ChaosDropped subclasses ConnectionError: the engine's replay-sink
    # handler catches it as OSError and counts replay_dropped_total
    assert issubclass(ChaosDropped, OSError)


@pytest.mark.chaos
def test_chaos_replay_append_corrupt_drives_quarantine(tmp_path):
    w = ReplayWriter(str(tmp_path), segment_records=2)
    plan = FaultPlan(seed=0, faults=[
        {"type": "corrupt", "site": "replay_append", "at": 1}])
    with chaos_plan(plan):
        for r in _rows(2):
            w.append(r)
    (path,) = scan_segments(str(tmp_path))
    # the sealed segment carries the flipped record: tail-time
    # validation quarantines the whole segment, no torn batch
    assert load_segment(path) == []
    assert os.path.exists(path + ".bad")


@pytest.mark.chaos
def test_chaos_replay_tail_corrupt_drives_quarantine(tmp_path):
    w = ReplayWriter(str(tmp_path), segment_records=2)
    for r in _rows(2):
        w.append(r)
    (path,) = scan_segments(str(tmp_path))
    plan = FaultPlan(seed=0, faults=[
        {"type": "corrupt", "site": "replay_tail", "at": 1}])
    with chaos_plan(plan):
        assert load_segment(path) == []
    assert plan.hits("replay_tail") == 1
    assert os.path.exists(path + ".bad")


# ------------------------------------------------- streaming master
def test_stream_wait_extend_dedupe_and_end(tmp_path):
    m = MasterService(store=FileStore(str(tmp_path / "ledger.snap")),
                      chunks_per_task=1, straggle_after_s=None)
    m.open_stream()
    # an open stream with nothing queued answers "wait", never "end"
    status, task = m.get_task(0, "t0")
    assert status == "wait" and task is None
    assert m.extend_dataset(["seg-a", "seg-b"]) == 2
    # dedupe is by chunk VALUE: re-scanning the same files adds nothing
    assert m.extend_dataset(["seg-a", "seg-b"]) == 0
    assert m.extend_dataset(["seg-b", "seg-c"]) == 1
    served = []
    for _ in range(3):
        status, t = m.get_task(0, "t0")
        assert status == "task"
        served.append(t["chunks"][0])
        m.task_finished(t["id"], "t0")
    assert served == ["seg-a", "seg-b", "seg-c"]
    # drained but stream open: "wait" (the tail may still grow)...
    status, _ = m.get_task(0, "t0")
    assert status == "wait"
    # ...and the task ids never collide across extends
    assert m.extend_dataset(["seg-d"]) == 1
    status, t = m.get_task(0, "t0")
    assert status == "task" and t["chunks"] == ["seg-d"]
    m.task_finished(t["id"], "t0")
    m.end_stream()
    # stream closed + everything done: the pass ends normally
    status, _ = m.get_task(0, "t0")
    assert status == "end"


def test_stream_flag_and_tasks_survive_recovery(tmp_path):
    snap = str(tmp_path / "ledger.snap")
    m1 = MasterService(store=FileStore(snap), chunks_per_task=1,
                       straggle_after_s=None)
    m1.open_stream()
    m1.extend_dataset(["seg-a", "seg-b"])
    status, t = m1.get_task(0, "t0")
    assert status == "task"
    m1.task_finished(t["id"], "t0")

    # a recovered master (same snapshot) still holds the open stream:
    # a drained queue answers "wait", and extend dedupes against the
    # recovered done/todo sets
    m2 = MasterService(store=FileStore(snap), chunks_per_task=1,
                       straggle_after_s=None)
    assert m2.extend_dataset(["seg-a", "seg-b"]) == 0
    status, t2 = m2.get_task(0, "t0")
    assert status == "task" and t2["chunks"] == ["seg-b"]
    m2.task_finished(t2["id"], "t0")
    assert m2.get_task(0, "t0")[0] == "wait"
    m2.end_stream()
    assert m2.get_task(0, "t0")[0] == "end"

    # the CLOSED flag also survives recovery
    m3 = MasterService(store=FileStore(snap), chunks_per_task=1,
                       straggle_after_s=None)
    assert m3.get_task(0, "t0")[0] == "end"


def test_extend_requires_open_stream(tmp_path):
    m = MasterService(store=FileStore(str(tmp_path / "s.snap")),
                      chunks_per_task=1, straggle_after_s=None)
    with pytest.raises(RuntimeError):
        m.extend_dataset(["seg-a"])


# ------------------------------------------------------- the tailer
def test_tailer_end_to_end_exactly_once(tmp_path):
    replay = tmp_path / "replay"
    w = ReplayWriter(str(replay), segment_records=4)
    for r in _rows(8):
        w.append(r)

    tailer = ReplayTailer(str(replay), batch_rows=2, scan_period_s=0.05,
                          poll_s=0.01)
    tailer.start()
    tailer.end_stream()  # drain mode: all traffic pre-sealed
    batches = list(tailer.reader())
    tailer.close()
    # 8 rows, 2 segments, re-batched at 2 rows/batch, in order
    assert batches == [_rows(2), _rows(2, start=2),
                       _rows(2, start=4), _rows(2, start=6)]
    # every segment committed exactly once: a second pass call over the
    # same (closed, fully-consumed) stream ends immediately
    assert list(tailer.reader(0)) == []


def test_tailer_quarantined_segment_skips_not_fails(tmp_path):
    replay = tmp_path / "replay"
    w = ReplayWriter(str(replay), segment_records=2)
    for r in _rows(6):
        w.append(r)
    a, b, c = scan_segments(str(replay))
    raw = bytearray(open(b, "rb").read())
    raw[len(raw) - 2] ^= 0xFF
    open(b, "wb").write(bytes(raw))

    tailer = ReplayTailer(str(replay), batch_rows=2, poll_s=0.01)
    tailer.start()
    tailer.end_stream()
    batches = list(tailer.reader())
    tailer.close()
    # the corrupt middle segment contributed NOTHING (its task
    # completed empty after quarantine); neighbors trained in full
    assert batches == [_rows(2), _rows(2, start=4)]
    assert os.path.exists(str(b) + ".bad")


def test_tailer_start_tolerates_preclosed_stream(tmp_path):
    replay = tmp_path / "replay"
    w = ReplayWriter(str(replay), segment_records=2)
    for r in _rows(2):
        w.append(r)
    t1 = ReplayTailer(str(replay), batch_rows=2, poll_s=0.01)
    t1.start()
    t1.end_stream()
    assert list(t1.reader()) == [_rows(2)]
    t1.close()
    # a rebuilt tailer over the same (fully-consumed) directory:
    # __init__ reopens the stream; closing it again and starting must
    # not raise even though extend has nothing fresh
    t2 = ReplayTailer(str(replay), batch_rows=2, poll_s=0.01)
    t2.end_stream()
    t2.start()
    assert list(t2.reader(0)) == []
    t2.close()
