"""The legacy PyDataProviderWrapper surface (pre-PyDP2 providers,
``python/paddle/trainer/PyDataProviderWrapper.py``): slot declarations +
``process(obj, filename)`` generators — exercised over the reference's
checked-in wrapper test data
(``paddle/trainer/tests/pydata_provider_wrapper_dir``, the
testPyDataWrapper.py contract)."""

import pathlib

import numpy as np
import pytest

from paddle_tpu.compat import install_paddle_alias

REF = pathlib.Path("/root/reference/paddle/trainer/tests/"
                   "pydata_provider_wrapper_dir")
needs_ref = pytest.mark.skipif(not REF.exists(), reason="needs reference")


def _make_provider():
    install_paddle_alias()
    from paddle.trainer.PyDataProviderWrapper import (DenseSlot, IndexSlot,
                                                      SparseNonValueSlot,
                                                      SparseValueSlot,
                                                      StringSlot, provider)

    # testPyDataWrapper.py's processNonSequenceData, line format:
    # index;sparse_ids;dense;sparse_values;string
    @provider(slots=[
        SparseNonValueSlot(10), DenseSlot(2), SparseValueSlot(10),
        StringSlot(1), IndexSlot(3)
    ], should_shuffle=False)
    def processNonSequenceData(obj, filename):
        with open(filename) as f:
            for line in f:
                slots_str = line.split(";")
                index = int(slots_str[0])
                non_values = [int(x) for x in slots_str[1].split()[1:]]
                dense = [float(x) for x in slots_str[2].split()[1:]]
                strs = slots_str[4].strip().split(" ", 1)[1]

                def _vm(s):
                    a, b = s.split(":")
                    return int(a), float(b)

                values = [_vm(x) for x in slots_str[3].split()[1:]]
                yield [non_values, dense, values, strs, index]

    return processNonSequenceData


@needs_ref
def test_wrapper_reads_reference_data():
    prov = _make_provider()
    assert [getattr(t, "type", None) for t in prov.input_types] == [
        "sparse_binary", "dense", "sparse_float", None, "index"]
    reader = prov.as_reader(
        str(REF / "test_pydata_provider_wrapper.list"), is_train=False)
    # the .list holds a source-root-relative path; resolve like the
    # reference (runs from the source root)
    import os
    cwd = os.getcwd()
    os.chdir("/root/reference/paddle")
    try:
        rows = list(reader())
    finally:
        os.chdir(cwd)
    assert len(rows) >= 2
    ids, dense, vals, s, idx = rows[0]
    assert ids == [1, 3, 5]
    assert len(dense) == 2 and isinstance(idx, int) and 0 <= idx < 3
    assert all(isinstance(p, tuple) and len(p) == 2 for p in vals)
    assert isinstance(s, str)


@needs_ref
def test_wrapper_feeds_training(tmp_path):
    """A wrapper-era provider drives an actual training run end-to-end
    (dense + index slots through the feeder)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: F401

    install_paddle_alias()
    from paddle.trainer.PyDataProviderWrapper import (DenseSlot, IndexSlot,
                                                      provider)
    from paddle_tpu.config import dsl
    from paddle_tpu.data.feeder import DataFeeder
    from paddle_tpu.data.reader import batch
    from paddle_tpu.optim import Momentum
    from paddle_tpu.trainer import SGD, events

    rng = np.random.RandomState(0)
    X = rng.randn(64, 4).astype(np.float32)
    Y = (X[:, 0] > 0).astype(int)
    data = tmp_path / "d.txt"
    data.write_text("\n".join(
        " ".join(map(str, X[i])) + ";" + str(Y[i]) for i in range(64)))
    lst = tmp_path / "f.list"
    lst.write_text(str(data) + "\n")

    @provider(slots=[DenseSlot(4), IndexSlot(2)], should_shuffle=False)
    def process(obj, filename):
        with open(filename) as f:
            for line in f:
                feats, lab = line.split(";")
                yield [[float(x) for x in feats.split()], int(lab)]

    reader = process.as_reader(str(lst))
    dsl.reset()
    x = dsl.data(name="x", size=4)
    lbl = dsl.data(name="label", size=2)
    out = dsl.fc(input=x, size=2, act="softmax")
    cost = dsl.classification_cost(input=out, label=lbl)
    trainer = SGD(cost=cost,
                  update_equation=Momentum(learning_rate=0.2, momentum=0.9))
    feeder = DataFeeder({"x": process.input_types[0],
                         "label": process.input_types[1]})
    errs = []
    trainer.train(batch(reader, 16), feeder=feeder, num_passes=8,
                  event_handler=lambda e: errs.append(
                      e.evaluator["classification_error"])
                  if isinstance(e, events.EndPass) else None)
    assert errs[-1] < errs[0] and errs[-1] < 0.2, errs
