"""Breadth coverage: the remaining v2 datasets, utils (dump_config, image
preprocessing, plotting, model diagram), and FP-anomaly mode."""

import numpy as np
import pytest

from paddle_tpu.v2 import dataset


def _take(reader, n=5):
    out = []
    for i, rec in enumerate(reader()):
        out.append(rec)
        if i + 1 >= n:
            break
    return out


def test_movielens_schema():
    recs = _take(dataset.movielens.train())
    for r in recs:
        uid, gender, age, job, mid, cats, title, score = r
        assert 0 <= uid < dataset.movielens.max_user_id()
        assert gender in (0, 1)
        assert 0 <= mid < dataset.movielens.max_movie_id()
        assert all(isinstance(c, int) for c in cats)
        assert 1.0 <= score[0] <= 5.0
    assert len(dataset.movielens.categories()) == 18


def test_conll05_schema():
    wd, vd, ld = dataset.conll05.get_dict()
    emb = dataset.conll05.get_embedding()
    assert emb.shape == (len(wd), 32)
    for rec in _take(dataset.conll05.test()):
        words, n2, n1, c0, p1, p2, verb, mark, labels = rec
        T = len(words)
        assert all(len(x) == T for x in (n2, n1, c0, p1, p2, verb, mark,
                                         labels))
        assert sum(mark) == 1  # exactly one predicate
        assert all(0 <= l < len(ld) for l in labels)


def test_wmt14_schema():
    for src, trg, nxt in _take(dataset.wmt14.train(1000)):
        assert trg[0] == dataset.wmt14.START_ID
        assert nxt[-1] == dataset.wmt14.END_ID
        assert trg[1:] == nxt[:-1]
        assert all(3 <= t < 1000 for t in src)
    s, t = dataset.wmt14.get_dict(100, reverse=True)
    assert s[0] == "<s>" and t[1] == "<e>"


def test_flowers_voc_schemas():
    for img, lab in _take(dataset.flowers.train()):
        assert img.shape == (3 * 32 * 32,) and img.dtype == np.float32
        assert 0 <= lab < dataset.flowers.N_CLASSES
    for img, mask in _take(dataset.voc2012.train()):
        assert img.shape == (3, 32, 32)
        assert mask.shape == (32, 32)
        assert mask.max() < dataset.voc2012.N_CLASSES


def test_sentiment_schema():
    wd = dataset.sentiment.get_word_dict()
    for words, lab in _take(dataset.sentiment.train()):
        assert lab in (0, 1)
        assert all(0 <= w < len(wd) for w in words)


def test_mq2007_formats():
    for rel, feats in _take(dataset.mq2007.train("pointwise")):
        assert feats.shape == (dataset.mq2007.FEATURE_DIM,)
    for lab, a, b in _take(dataset.mq2007.train("pairwise")):
        assert a.shape == b.shape == (dataset.mq2007.FEATURE_DIM,)
    for rels, mat in _take(dataset.mq2007.train("listwise")):
        assert mat.shape == (len(rels), dataset.mq2007.FEATURE_DIM)


def test_mq2007_real_letor_parse(tmp_path):
    """The genuine LETOR text format parses (real-tier path)."""
    txt = ("2 qid:10 1:0.5 2:0.1 46:0.9 #doc1\n"
           "0 qid:10 1:0.1 2:0.2 #doc2\n"
           "1 qid:11 1:0.9 #doc3\n")
    p = tmp_path / "train.txt"
    p.write_text(txt)
    q = dataset.mq2007._parse_letor(str(p))
    assert set(q) == {"10", "11"}
    rel, feats = q["10"]
    assert list(rel) == [2.0, 0.0]
    assert feats[0, 0] == np.float32(0.5) and feats[0, 45] == np.float32(0.9)


def test_movielens_real_archive_parse(tmp_path, monkeypatch):
    """The genuine ml-1m zip layout parses (real-tier path)."""
    import zipfile
    d = tmp_path / "movielens"
    d.mkdir()
    with zipfile.ZipFile(d / "ml-1m.zip", "w") as z:
        z.writestr("ml-1m/users.dat",
                   "1::M::25::4::55455\n2::F::35::7::55117\n")
        z.writestr("ml-1m/movies.dat",
                   "10::Toy Story (1995)::Animation|Comedy\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::10::5::978300760\n2::10::3::978302109\n")
    monkeypatch.setattr(dataset.common, "DATA_HOME", str(tmp_path))
    recs = list(dataset.movielens.train()())
    assert len(recs) == 2  # neither lands in the 1-in-10 test split
    uid, gender, age, job, mid, cats, title, score = recs[0]
    assert (uid, gender, mid, score) == (1, 1, 10, [5.0])
    assert len(cats) == 2 and len(title) == 2  # two genres, "Toy Story"


# ------------------------------------------------------------------- utils
def test_image_transforms():
    from paddle_tpu.utils import image
    rng = np.random.RandomState(0)
    im = rng.rand(48, 64, 3).astype(np.float32)
    assert image.resize_short(im, 32).shape[0] == 32  # short side
    assert image.center_crop(im, 32).shape[:2] == (32, 32)
    assert image.random_crop(im, 32, rng).shape[:2] == (32, 32)
    out = image.simple_transform(im, 40, 32, is_train=True, rng=rng,
                                 mean=[0.5, 0.5, 0.5])
    assert out.shape == (3, 32, 32)
    flipped = image.left_right_flip(im)
    np.testing.assert_allclose(flipped[:, ::-1], im)


def test_ploter_accumulates():
    from paddle_tpu.utils.plot import Ploter
    p = Ploter("train", "test")
    p.append("train", 0, 1.0)
    p.append("train", 1, 0.5)
    assert p.series["train"] == [(0.0, 1.0), (1.0, 0.5)]
    p.plot()  # headless: must not raise
    p.reset()
    assert p.series["train"] == []


def test_model_diagram(tmp_path):
    from paddle_tpu.config import dsl
    from paddle_tpu.utils.diagram import make_diagram
    dsl.reset()
    x = dsl.data(name="x", size=4)
    y = dsl.fc(input=x, size=2, name="out")
    dot = make_diagram(dsl.current_graph(), str(tmp_path / "m.dot"))
    assert '"x" -> "out";' in dot
    assert (tmp_path / "m.dot").read_text() == dot


def test_dump_config(tmp_path, capsys):
    cfg = tmp_path / "c.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=32, learning_rate=0.1)\n"
        "x = data_layer(name='x', size=4)\n"
        "outputs(fc_layer(input=x, size=2))\n")
    from paddle_tpu.utils.dump_config import main
    assert main([str(cfg)]) == 0
    out = capsys.readouterr().out
    assert "batch_size: 32" in out and 'type: "fc"' in out


def test_fp_anomaly_mode():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.utils import fp
    fp.enable_fp_anomaly()
    try:
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.float32(-1.0)).block_until_ready()
    finally:
        fp.disable_fp_anomaly()
    # and normal computation is unaffected afterwards
    assert float(jax.jit(lambda x: x + 1)(jnp.float32(1.0))) == 2.0


def test_mix_readers_multidataprovider_contract():
    """reader.mix: per-round ratio composition, non-main restart, main
    ends the pass (MultiDataProvider.cpp:80-110)."""
    from paddle_tpu.data.reader import batch, mix

    def ra():  # main: 6 samples
        return iter(["a%d" % i for i in range(6)])

    def rb():  # short: restarts
        return iter(["b%d" % i for i in range(2)])

    mixed = mix([(lambda: ra(), 2), (lambda: rb(), 1)], main=0)
    got = list(mixed())
    # rounds of 2 a's + 1 b until a is exhausted; b wraps around
    assert got == ["a0", "a1", "b0", "a2", "a3", "b1", "a4", "a5", "b0"]
    # batch size divisible by sum(ratios) gives exact composition
    bs = list(batch(mixed, 3)())
    assert all(sum(s.startswith("a") for s in b) == 2 for b in bs)
    import pytest
    with pytest.raises(ValueError):
        mix([(ra, 0)])
    with pytest.raises(ValueError):
        mix([])
    with pytest.raises(ValueError):
        mix([(ra, 1)], main=1)
    # a main whose length is not a multiple of its ratio keeps its tail
    def r5():
        return iter(["a%d" % i for i in range(5)])
    tail = list(mix([(lambda: r5(), 2), (lambda: rb(), 1)], main=0)())
    assert "a4" in tail and tail[-1] == "a4"
    # an empty non-main sub-reader is a loud error, not a hang/crash
    with pytest.raises(ValueError, match="no samples"):
        list(mix([(lambda: r5(), 1), (lambda: iter([]), 1)], main=0)())
