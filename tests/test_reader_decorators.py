"""Port of the reference's reader decorator tests
(`python/paddle/v2/reader/tests/decorator_test.py`): map_readers,
buffered (incl. the it-actually-buffers timing check), compose (aligned,
not-aligned raising, not-aligned discarding), chain, shuffle, firstn, mix.
"""

import time

import pytest

import paddle_tpu.v2 as paddle

reader = paddle.reader


def reader_creator_10(dur=0.0):
    def r():
        for i in range(10):
            if dur:
                time.sleep(dur)
            yield i
    return r


def test_map():
    d = {"h": 0, "i": 1}

    def read():
        yield "h"
        yield "i"

    r = reader.map_readers(lambda x: d[x], read)
    assert list(r()) == [0, 1]


def test_buffered_preserves_order():
    for size in range(1, 20):
        assert list(reader.buffered(reader_creator_10(), size)()) \
            == list(range(10))


def test_buffered_actually_buffers():
    b = reader.buffered(reader_creator_10(0.03), 10)
    last = time.time()
    for i in b():
        elapsed = time.time() - last
        if i == 0:
            time.sleep(0.3)  # let the worker fill the buffer
        else:
            assert elapsed < 0.05, "reads should hit the buffer"
        last = time.time()


def test_compose_aligned():
    r = reader.compose(reader_creator_10(), reader_creator_10())
    assert list(r()) == [(i, i) for i in range(10)]


def test_compose_not_aligned_raises():
    r = reader.compose(
        reader.chain(reader_creator_10(), reader_creator_10()),
        reader_creator_10())
    total = 0
    with pytest.raises(reader.ComposeNotAligned):
        for _ in r():
            total += 1
    assert total == 10  # the aligned prefix is yielded before the raise


def test_compose_not_aligned_no_check_discards_tail():
    r = reader.compose(
        reader.chain(reader_creator_10(), reader_creator_10()),
        reader_creator_10(), check_alignment=False)
    assert len(list(r())) == 10  # not 20: trailing outputs discarded


def test_chain():
    c = reader.chain(reader_creator_10(), reader_creator_10())
    assert list(c()) == [i % 10 for i in range(20)]


def test_shuffle():
    for size, check_eq in [(0, True), (1, True), (10, False), (100, False)]:
        got = list(reader.shuffle(reader_creator_10(), size)())
        assert len(got) == 10
        if check_eq:
            assert got == list(range(10))
        assert sorted(got) == list(range(10))


def test_firstn():
    assert list(reader.firstn(reader_creator_10(), 3)()) == [0, 1, 2]
    assert len(list(reader.firstn(reader_creator_10(), 100)())) == 10


def test_mix_ratios():
    a = reader_creator_10()

    def b():
        for i in range(20):
            yield 100 + i

    got = list(reader.mix([(a, 1), (b, 2)], main=0)())
    # main reader (a) exhausts after 10; b contributes ~2 per a-sample
    assert [x for x in got if x < 100] == list(range(10))
    assert sum(1 for x in got if x >= 100) >= 10
