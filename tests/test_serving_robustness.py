"""Serving robustness pins (ISSUE r09 satellite): deadline-exceeded is a
typed error (504) not a 500, shed requests carry retry-after, SIGTERM
drains in-flight work, and a malformed request coalesced into a batch
cannot poison its neighbors (its lane is masked out, they still answer).
The subprocess soak test (real SIGTERM against the real CLI server under
sustained HTTP load) is marked ``slow`` to keep tier-1 within budget."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import jax

from paddle_tpu.config import dsl
from paddle_tpu.data import dense_vector, integer_value
from paddle_tpu.serving import (BadRequest, DeadlineExceeded, Overloaded,
                                ServingClient, ServingEngine,
                                ServingPredictor, ShuttingDown,
                                install_signal_handlers, make_server)

DIM, CLASSES = 6, 3


def _predictor(vocab_check=False):
    dsl.reset()
    x = dsl.data(name="x", size=DIM)
    lab = dsl.data(name="label", size=CLASSES)
    hid = dsl.fc(input=x, size=8, act="relu", name="hid")
    out = dsl.fc(input=hid, size=CLASSES, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    from paddle_tpu.core.network import Network
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    feeding = {"x": dense_vector(DIM), "label": integer_value(CLASSES)}
    return ServingPredictor(graph, params, ["out"], feeding,
                            batch_buckets=[1, 2, 4])


@pytest.fixture(scope="module")
def pred():
    p = _predictor()
    p.warmup()
    return p


def _slow(pred, delay_s):
    """Wrap predict_rows with a synthetic stall (monkeypatching the
    bound method on the ENGINE's view only)."""
    orig = pred.predict_rows

    def slow(rows, lane_valid=None):
        time.sleep(delay_s)
        return orig(rows, lane_valid)

    return orig, slow


def test_deadline_exceeded_is_typed_not_500(pred):
    eng = ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                        queue_depth=16).start(warmup=False)
    orig, slow = _slow(pred, 0.08)
    pred.predict_rows = slow
    try:
        sample = ([0.0] * DIM, 0)
        # (a) computed-but-late: the only in-flight request, compute
        # takes 80 ms against a 20 ms deadline
        with pytest.raises(DeadlineExceeded):
            eng.infer(sample, deadline_ms=20)
        # (b) expired-in-queue: stall the worker with a long request,
        # then enqueue one whose deadline lapses while it waits
        first = eng.submit(sample)
        late = eng.submit(sample, deadline_ms=10)
        first.event.wait(30.0)
        late.event.wait(30.0)
        assert isinstance(late.error, DeadlineExceeded)
        assert eng.metrics.snapshot()["deadline_exceeded_total"] >= 2
    finally:
        pred.predict_rows = orig
        eng.shutdown()


def test_deadline_exceeded_http_status_504(pred):
    eng = ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                        queue_depth=16).start(warmup=False)
    server = make_server(eng, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    orig, slow = _slow(pred, 0.08)
    pred.predict_rows = slow
    try:
        client = ServingClient(port=server.server_address[1])
        with pytest.raises(DeadlineExceeded) as ei:
            client.score(([0.0] * DIM, 0), deadline_ms=20)
        assert ei.value.status == 504  # typed, not a 500
    finally:
        pred.predict_rows = orig
        server.shutdown()
        eng.shutdown()


def test_load_shedding_carries_retry_after(pred):
    eng = ServingEngine(pred, max_batch=1, batch_timeout_ms=1.0,
                        queue_depth=2, shed_watermark=2).start(warmup=False)
    orig, slow = _slow(pred, 0.1)
    pred.predict_rows = slow
    server = make_server(eng, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        sample = ([0.0] * DIM, 0)
        admitted = []
        shed = None
        # flood: the worker is stalled, so the queue fills to the
        # watermark and the next submit must shed
        for _ in range(8):
            try:
                admitted.append(eng.submit(sample))
            except Overloaded as e:
                shed = e
                break
        assert shed is not None, "flood never shed"
        assert shed.retry_after_ms and shed.retry_after_ms > 0
        assert eng.metrics.snapshot()["shed_total"] >= 1
        # the HTTP form: 429 + Retry-After header + typed body
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1",
                                          server.server_address[1],
                                          timeout=30)
        conn.request("POST", "/v1/score",
                     body=json.dumps({"sample": sample}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        if resp.status == 429:  # raced the drain of the stalled queue
            assert resp.headers["Retry-After"]
            assert body["error"]["code"] == "overloaded"
            assert body["error"]["retry_after_ms"] > 0
        conn.close()
        for r in admitted:
            r.event.wait(60.0)
    finally:
        pred.predict_rows = orig
        server.shutdown()
        eng.shutdown()


def test_sigterm_drains_in_flight_work(pred):
    """Real SIGTERM to this process: the installed handler closes
    admission immediately (new submits -> ShuttingDown), every queued
    request still completes, and the worker exits."""
    eng = ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                        queue_depth=32).start(warmup=False)
    orig, slow = _slow(pred, 0.05)
    pred.predict_rows = slow
    prev = install_signal_handlers(eng)
    try:
        sample = ([0.0] * DIM, 0)
        inflight = [eng.submit(sample) for _ in range(6)]
        os.kill(os.getpid(), signal.SIGTERM)
        # the handler runs in the main thread between bytecodes; give it
        # a beat, then admission must be closed
        deadline = time.time() + 10
        while not eng.draining and time.time() < deadline:
            time.sleep(0.01)
        assert eng.draining
        with pytest.raises(ShuttingDown):
            eng.submit(sample)
        # every in-flight request completes with a real answer
        for r in inflight:
            assert r.event.wait(60.0)
            assert r.error is None and "outputs" in r.result
    finally:
        pred.predict_rows = orig
        for sig, h in prev.items():
            signal.signal(sig, h)
        eng.shutdown()


def test_malformed_lane_cannot_poison_coalesced_batch(pred):
    """Two requests coalesced into one batch, one malformed (id outside
    the declared label range -> host-side conversion failure): the bad
    lane is masked out and answered BadRequest; its neighbor's answer
    matches a clean solo run."""
    eng = ServingEngine(pred, max_batch=4,
                        batch_timeout_ms=120.0,  # force coalescing
                        queue_depth=16).start(warmup=False)
    try:
        good_sample = (list(np.arange(DIM) / DIM), 1)
        bad_sample = ([0.0] * DIM, 99)  # label way out of range
        good = eng.submit(good_sample)
        bad = eng.submit(bad_sample)
        assert good.event.wait(60.0) and bad.event.wait(60.0)
        assert isinstance(bad.error, BadRequest)
        assert "99" in str(bad.error)
        assert good.error is None
        # the answered batch really contained both lanes
        snap = eng.metrics.snapshot()
        assert snap["bad_request_total"] >= 1
        assert any(k.startswith("b2") for k in snap["bucket_hits"])
        # neighbor parity vs a clean solo call
        solo = eng.infer(good_sample)
        np.testing.assert_allclose(
            np.asarray(good.result["outputs"]["out"]),
            np.asarray(solo["outputs"]["out"]), rtol=1e-5)
    finally:
        eng.shutdown()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_worker_death_answers_in_flight_and_closes_admission():
    """A bug escaping the batch path (e.g. a RecompileError from the
    hardened guard) must not strand callers: the collected batch's
    requests are answered with a typed internal error, the queue is
    flushed, and later submits are rejected instead of enqueued into a
    queue nothing drains."""
    from paddle_tpu.serving.errors import ServingError
    p = _predictor()
    p.warmup()
    eng = ServingEngine(p, max_batch=2, batch_timeout_ms=1.0,
                        queue_depth=8).start(warmup=False)

    def boom(rows, lane_valid=None):
        raise RuntimeError("synthetic worker bug")

    p.predict_rows = boom
    try:
        sample = ([0.0] * DIM, 0)
        req = eng.submit(sample)
        assert req.event.wait(30.0), "in-flight request left hanging"
        assert isinstance(req.error, ServingError)
        assert "synthetic worker bug" in str(req.error)
        # the worker is dead; admission must say so, not enqueue
        deadline = time.time() + 10
        while eng.fatal is None and time.time() < deadline:
            time.sleep(0.01)
        with pytest.raises(ServingError) as ei:
            eng.submit(sample)
        assert not isinstance(ei.value, (Overloaded, BadRequest))
        assert eng.metrics.snapshot()["internal_error_total"] >= 1
    finally:
        eng.shutdown()


def test_draining_healthz_and_shutdown_idempotent(pred):
    eng = ServingEngine(pred, batch_timeout_ms=1.0).start(warmup=False)
    server = make_server(eng, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        client = ServingClient(port=server.server_address[1])
        assert client.healthz()["status"] == "ok"
        eng.begin_drain()
        from paddle_tpu.serving.errors import ServingError
        try:
            h = client.healthz()
            status = h["status"]
        except ServingError as e:  # 503 surfaces as typed error
            status = "draining" if e.status == 503 else "?"
        assert status == "draining"
        eng.shutdown()
        eng.shutdown()  # idempotent
    finally:
        server.shutdown()


SOAK_CONFIG = textwrap.dedent("""
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.data.types import dense_vector, integer_value
    from paddle_tpu.optim import Momentum

    x = dsl.data(name="x", size=6)
    lab = dsl.data(name="label", size=3)
    hid = dsl.fc(input=x, size=8, act="relu", name="hid")
    out = dsl.fc(input=hid, size=3, act="softmax", name="out")
    cost = dsl.classification_cost(input=out, label=lab)
    outputs = [out]
    optimizer = Momentum(learning_rate=0.1, momentum=0.9)
    feeding = {"x": dense_vector(6), "label": integer_value(3)}

    def train_reader():
        rng = np.random.RandomState(0)
        yield [(rng.randn(6).astype(np.float32), 0) for _ in range(8)]
""")


@pytest.mark.slow
def test_serving_soak_sigterm_subprocess(tmp_path):
    """The full production exit path, out of process: the real CLI
    server under sustained HTTP load receives a real SIGTERM, finishes
    what it accepted, and exits 0."""
    config = tmp_path / "conf.py"
    config.write_text(SOAK_CONFIG)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.trainer.cli",
         "--config", str(config), "--job", "serve", "--port", "0",
         "--max_batch", "4", "--batch_timeout_ms", "2",
         "--queue_depth", "64"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd="/root/repo")
    try:
        # the ready line carries the ephemeral port
        line = ""
        deadline = time.time() + 240
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("serving on http://"):
                break
        assert line.startswith("serving on http://"), line
        port = int(line.split("http://127.0.0.1:")[1].split(" ")[0])
        client = ServingClient(port=port, timeout=60)
        stop = threading.Event()
        answered, errors = [], []

        def load():
            rng = np.random.RandomState(1)
            while not stop.is_set():
                try:
                    r = client.score((rng.randn(6).tolist(), 0))
                    answered.append(r)
                except Exception as e:  # noqa: BLE001 — counted
                    errors.append(e)
                    time.sleep(0.01)

        threads = [threading.Thread(target=load) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(3.0)  # sustained load
        assert client.healthz()["status"] == "ok"
        proc.send_signal(signal.SIGTERM)
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(30.0)
        rc = proc.wait(timeout=120)
        assert rc == 0
        assert len(answered) > 10  # the soak really served traffic
        # post-SIGTERM failures must be typed (ShuttingDown / conn
        # reset), never a 500 body
        from paddle_tpu.serving.errors import ServingError
        for e in errors:
            if isinstance(e, ServingError):
                assert e.status != 500
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
