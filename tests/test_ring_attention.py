"""Sequence-parallel attention tests on the 8-device virtual CPU mesh.

Verifies ring attention and Ulysses all-to-all attention equal the
single-device reference (values and gradients) with ragged kv masks and
causal masking — the sharded path must be a pure re-layout of the math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.ops.attention import mha_reference
from paddle_tpu.parallel.ring import make_ring_attention


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("seq",))


def _data(rng, B=2, N=4, T=32, D=8):
    q = jnp.asarray(rng.normal(size=(B, N, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, N, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, N, T, D)), jnp.float32)
    lens = rng.integers(T // 2, T + 1, size=B)
    mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
    return q, k, v, jnp.asarray(mask)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_seq_parallel_attention_matches_reference(kind, causal):
    rng = np.random.default_rng(0)
    q, k, v, mask = _data(rng)
    mesh = _mesh(4)
    fn = make_ring_attention(mesh, "seq", kind=kind, causal=causal)
    out = fn(q, k, v, mask)
    ref = mha_reference(q, k, v, mask, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_seq_parallel_attention_grads(kind):
    rng = np.random.default_rng(1)
    q, k, v, mask = _data(rng, T=16)
    mesh = _mesh(4)
    fn = make_ring_attention(mesh, "seq", kind=kind, causal=True)

    def loss(fn_, q_, k_, v_):
        return jnp.sum(fn_(q_, k_, v_, mask) ** 2)

    gq, gk, gv = jax.grad(lambda *a: loss(fn, *a), (0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(
        lambda *a: loss(lambda q_, k_, v_, m: mha_reference(
            q_, k_, v_, m, causal=True), *a), (0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_jits_and_shards():
    """jit(fn) must compile with sharded inputs and produce sharded output."""
    rng = np.random.default_rng(2)
    q, k, v, mask = _data(rng, T=64)
    mesh = _mesh(8)
    fn = jax.jit(make_ring_attention(mesh, "seq", kind="ring", causal=True))
    out = fn(q, k, v, mask)
    ref = mha_reference(q, k, v, mask, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
