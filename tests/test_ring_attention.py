"""Sequence-parallel attention tests on the 8-device virtual CPU mesh.

Verifies ring attention and Ulysses all-to-all attention equal the
single-device reference (values and gradients) with ragged kv masks and
causal masking — the sharded path must be a pure re-layout of the math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.ops.attention import mha_reference
from paddle_tpu.parallel.ring import make_ring_attention


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("seq",))


def _data(rng, B=2, N=4, T=32, D=8):
    q = jnp.asarray(rng.normal(size=(B, N, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, N, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, N, T, D)), jnp.float32)
    lens = rng.integers(T // 2, T + 1, size=B)
    mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
    return q, k, v, jnp.asarray(mask)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_seq_parallel_attention_matches_reference(kind, causal):
    rng = np.random.default_rng(0)
    q, k, v, mask = _data(rng)
    mesh = _mesh(4)
    fn = make_ring_attention(mesh, "seq", kind=kind, causal=causal)
    out = fn(q, k, v, mask)
    ref = mha_reference(q, k, v, mask, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_seq_parallel_attention_grads(kind):
    rng = np.random.default_rng(1)
    q, k, v, mask = _data(rng, T=16)
    mesh = _mesh(4)
    fn = make_ring_attention(mesh, "seq", kind=kind, causal=True)

    def loss(fn_, q_, k_, v_):
        return jnp.sum(fn_(q_, k_, v_, mask) ** 2)

    gq, gk, gv = jax.grad(lambda *a: loss(fn, *a), (0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(
        lambda *a: loss(lambda q_, k_, v_, m: mha_reference(
            q_, k_, v_, m, causal=True), *a), (0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_jits_and_shards():
    """jit(fn) must compile with sharded inputs and produce sharded output."""
    rng = np.random.default_rng(2)
    q, k, v, mask = _data(rng, T=64)
    mesh = _mesh(8)
    fn = jax.jit(make_ring_attention(mesh, "seq", kind="ring", causal=True))
    out = fn(q, k, v, mask)
    ref = mha_reference(q, k, v, mask, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------------- config-reachable knob
# VERDICT r04 weak #5: sequence parallelism must be reachable from a user
# config string, not only as library code.

def test_seq_parallel_is_config_reachable():
    """A user config string (`multi_head_attention(seq_parallel=...)`)
    + a seq-axis mesh (`create_mesh(n_seq=...)`) turns on sharded
    attention inside the ordinary SGD trainer — outputs match the same
    config trained without the mesh, and the compiled step carries the
    ring collective."""
    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.core.network import Network
    from paddle_tpu.parallel import create_mesh

    def build(sp):
        dsl.reset()
        x = dsl.data(name="x", size=16, is_sequence=True)
        att = dsl.multi_head_attention(x, num_heads=4, seq_parallel=sp,
                                       name="att")
        out = dsl.fc(input=att, size=4, act="softmax", name="out")
        return dsl.current_graph()

    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.normal(size=(2, 16, 16)), jnp.float32)
    mask = jnp.ones((2, 16), jnp.float32)
    feed = {"x": Argument(value=v, mask=mask)}

    net = Network(build("ring"), outputs=["out"])
    params = net.init_params(jax.random.PRNGKey(0))
    mesh = create_mesh(n_data=1, n_seq=8)
    assert "seq" in mesh.shape and mesh.shape["seq"] == 8
    sharded = net.apply(params, feed, train=False, mesh=mesh)["out"].value
    dense = net.apply(params, feed, train=False)["out"].value  # no mesh
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense),
                               rtol=1e-5, atol=1e-5)
    # the sharded program really contains the ring collective (post-SPMD
    # partitioning — the pre-partition StableHLO only carries shardings)
    hlo = jax.jit(lambda p, f: net.apply(p, f, mesh=mesh)["out"].value
                  ).lower(params, feed).compile().as_text()
    assert "collective-permute" in hlo


def test_seq_parallel_trains_through_sgd():
    """End-to-end: the knob works through the SGD trainer (mesh passed
    once, config string does the rest) and the model learns."""
    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel import create_mesh
    from paddle_tpu.trainer import events as ev
    from paddle_tpu.trainer.trainer import SGD

    dsl.reset()
    x = dsl.data(name="x", size=8, is_sequence=True)
    att = dsl.multi_head_attention(x, num_heads=8,
                                   seq_parallel="ulysses", name="att")
    pooled = dsl.pooling(input=att)
    out = dsl.fc(input=pooled, size=2, act="softmax")
    cost = dsl.classification_cost(input=out,
                                   label=dsl.data(name="lab", size=2))
    rng = np.random.default_rng(1)
    X = rng.normal(size=(32, 16, 8)).astype(np.float32)
    Y = (X[:, :, 0].mean(axis=1) > 0).astype(np.int32)

    def reader():
        for i in range(0, 32, 8):
            yield {"x": Argument(value=jnp.asarray(X[i:i + 8]),
                                 mask=jnp.ones((8, 16), jnp.float32)),
                   "lab": Argument(value=jnp.asarray(Y[i:i + 8]))}

    mesh = create_mesh(n_data=1, n_seq=8)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-2),
             mesh=mesh)
    costs = []
    tr.train(reader, num_passes=8,
             event_handler=lambda e: costs.append(float(e.cost))
             if isinstance(e, ev.EndIteration) else None)
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_seq2seq_model_seq_parallel_knob():
    """models/seq2seq.py grows the long-context encoder block from a
    model-level string; graph contains the seq-parallel attention."""
    from paddle_tpu.config import dsl
    from paddle_tpu.models import seq2seq_attention

    dsl.reset()
    seq2seq_attention(src_vocab=20, trg_vocab=12, embed_dim=16, hidden=16,
                      seq_parallel="ring")
    g = dsl.current_graph()
    att = g.layers["enc_self_att"]
    assert att.type == "multi_head_attention"
    assert att.attrs["seq_parallel"] == "ring"
