"""chunking.conf end-to-end: the reference's own trainer-test config
(``paddle/trainer/tests/chunking.conf``, the linear-CRF chunker its
``test_Trainer`` suite trains) runs UNMODIFIED through the CLI on proto
shards generated from the REAL checked-in CoNLL-2000 corpus — the shards
``gen_proto_data.py`` would produce (its dict/feature pipeline exec'd
verbatim from the demo provider, which shares it; the varint framing is
``data/protodata.py:write_shard``). Closes the one missing piece of the
chunking story: the reference ships the config + corpus but not the
generated ``train_proto.bin``.
"""

import os
import pathlib
import re
import shutil
import sys

import pytest

REF_TESTS = pathlib.Path("/root/reference/paddle/trainer/tests")
TAG_PROVIDER = pathlib.Path(
    "/root/reference/v1_api_demo/sequence_tagging/dataprovider.py")
needs_ref = pytest.mark.skipif(not REF_TESTS.exists(),
                               reason="needs reference")


def _ref_feature_ns():
    """Exec the reference's feature/dictionary pipeline (the demo
    provider and gen_proto_data.py share patterns/make_features/
    create_dictionaries/dict_label verbatim) with the documented py2
    shims."""
    import gzip as _gz

    from paddle_tpu.compat import install_paddle_alias
    install_paddle_alias()
    src = TAG_PROVIDER.read_text().replace(".iteritems()", ".items()")
    # the py2 shim lives in the exec'd module's OWN globals — no
    # builtins mutation, so the rest of the suite stays py3-strict
    ns = {"__name__": "ref_feature_pipeline", "xrange": range}
    exec(compile(src, str(TAG_PROVIDER), "exec"), ns)

    class _GzipText:
        @staticmethod
        def open(filename, mode="rt"):
            return _gz.open(filename, "rt")

    ns["gzip"] = _GzipText
    return ns


def _sentences(path):
    cur = []
    for ln in open(path):
        ln = ln.strip()
        if not ln:
            if cur:
                yield cur
                cur = []
            continue
        cur.append(ln.split(" "))
    if cur:
        yield cur


def _gen_proto_shard(ns, dicts, oov_policy, src_txt, out_path):
    """Port of ``gen_proto_file`` (gen_proto_data.py:166-240): slot 0 =
    sparse pattern features, slots 1-3 = word/pos/chunk INDEX;
    OOV_POLICY_IGNORE writes the 0xffffffff sentinel exactly as the
    reference does."""
    from paddle_tpu.data.protodata import write_shard
    from paddle_tpu.proto import DataHeader, DataSample, SlotDef
    IGNORE, USE, ERROR = (ns["OOV_POLICY_IGNORE"], ns["OOV_POLICY_USE"],
                          ns["OOV_POLICY_ERROR"])
    n_orig = ns["num_original_columns"]
    header = DataHeader()
    sd = header.slot_defs.add()
    sd.type = SlotDef.VECTOR_SPARSE_NON_VALUE
    sd.dim = sum(len(dicts[i]) for i in range(n_orig, len(dicts)))
    for i in range(n_orig):
        sd = header.slot_defs.add()
        sd.type = SlotDef.INDEX
        sd.dim = len(dicts[i])
    samples = []
    for sentence in _sentences(src_txt):
        ns["make_features"](sentence)
        first = True
        for features in sentence:
            s = DataSample()
            vec = s.vector_slots.add()
            dim = 0
            for i in range(n_orig, len(dicts)):
                fid = dicts[i].get(features[i], -1)
                if fid != -1:
                    vec.ids.append(dim + fid)
                elif oov_policy[i] == ERROR:
                    raise AssertionError(f"unknown token {features[i]!r}")
                elif oov_policy[i] == USE:
                    vec.ids.append(dim + 0)
                dim += len(dicts[i])
            for i in range(n_orig):
                tid = dicts[i].get(features[i], -1)
                if tid != -1:
                    s.id_slots.append(tid)
                elif oov_policy[i] == IGNORE:
                    s.id_slots.append(0xFFFFFFFF)
                elif oov_policy[i] == ERROR:
                    raise AssertionError(f"unknown token {features[i]!r}")
                else:
                    s.id_slots.append(0)
            s.is_beginning = first
            first = False
            samples.append(s)
    write_shard(str(out_path), header, samples)
    return header


@needs_ref
def test_chunking_conf_trains_on_generated_proto_shards(tmp_path, capsys):
    import gzip

    import jax
    jax.config.update("jax_platforms", "cpu")
    # the provider's create_dictionaries reads gzip text; stage the
    # corpus the way the demo expects
    src_gz = tmp_path / "train.txt.gz"
    with open(REF_TESTS / "train.txt", "rb") as fin, \
            gzip.open(src_gz, "wb") as fout:
        shutil.copyfileobj(fin, fout)
    ns = _ref_feature_ns()
    # gen_proto_data.py __main__ exact recipe: cutoffs [3,1,0]+[3]*P,
    # policies [IGNORE, ERROR, ERROR]+[IGNORE]*P, chunk dict pinned
    P = len(ns["patterns"])
    cutoff = [3, 1, 0] + [3] * P
    oov = [ns["OOV_POLICY_IGNORE"], ns["OOV_POLICY_ERROR"],
           ns["OOV_POLICY_ERROR"]] + [ns["OOV_POLICY_IGNORE"]] * P
    dicts = ns["create_dictionaries"](str(src_gz), cutoff, oov)
    dicts[2] = ns["dict_label"]
    shard_dir = tmp_path / "trainer" / "tests"
    shard_dir.mkdir(parents=True)
    header = _gen_proto_shard(ns, dicts, oov, REF_TESTS / "train.txt",
                              shard_dir / "train_proto.bin")
    # the config hardcodes features size 4339 — the dicts generated from
    # this corpus must reproduce it exactly (they were generated FROM it)
    assert header.slot_defs[0].dim == 4339
    _gen_proto_shard(ns, dicts, oov, REF_TESTS / "test.txt",
                     shard_dir / "test_proto.bin")
    (shard_dir / "train_files.txt").write_text(
        str(shard_dir / "train_proto.bin") + "\n")
    (shard_dir / "test_files.txt").write_text(
        str(shard_dir / "test_proto.bin") + "\n")
    shutil.copy(REF_TESTS / "chunking.conf", tmp_path / "chunking.conf")

    from paddle_tpu.trainer import cli
    old = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = cli.main(["--config", str(tmp_path / "chunking.conf"),
                       "--job", "train", "--num_passes", "3",
                       "--test_period", "1", "--log_period", "0"])
    finally:
        os.chdir(old)
    assert rc == 0
    out = capsys.readouterr().out
    errs = [float(m.group(1)) for m in re.finditer(r"error=([0-9.eE+-]+)",
                                                   out)]
    assert errs, out
    # the sum evaluator counts wrongly-decoded sequences: it must FALL
    # as the CRF trains (208 train sequences; linear CRF on these
    # features fits them fast)
    assert errs[-1] < errs[0], errs
