"""Distributed tracing: one trace_id end to end through the fleet.

The r15 acceptance spine: a scored request driven through a 2-replica
router with an induced failover yields a SINGLE trace whose spans
reconstruct the client-observed latency — the ``client.request`` root's
wall time lands within 5% of the latency the caller measured around
``client.score``, the failover reads as two sibling ``router.attempt``
spans (one error, one ok) under one ``router.dispatch``, and the JSONL
dump satisfies the TRACE_* artifact schema (PT401: non-empty spans,
monotone timestamps, parent refs resolve). Plus the propagation
contracts: hedges as sibling attempts, the ``X-Trace-Id`` echo on typed
errors and fenced-standby 503s, and the master RPC codec pairing
``rpc.<method>`` / ``rpc.server.<method>`` under one trace.
"""

import threading
import time

import numpy as np
import pytest

import jax

from paddle_tpu.config import dsl
from paddle_tpu.data import dense_vector, integer_value
from paddle_tpu.obs import trace
from paddle_tpu.serving import (BadRequest, EngineTransport,
                                ReplicaRouter, ServingClient,
                                ServingEngine, ServingPredictor,
                                Unavailable, make_router_server)
from paddle_tpu.serving.router import PendingCall
from paddle_tpu.testing import chaos

DIM, CLASSES = 8, 4
SAMPLE = ((np.arange(DIM, dtype=float) / DIM).tolist(), 1)
HEX = set("0123456789abcdef")


@pytest.fixture
def tracer():
    t = trace.install(trace.Tracer("test"))
    try:
        yield t
    finally:
        trace.install(None)


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """Two in-process replicas behind the router HTTP frontend (the
    shared AOT cache keeps the 1-core warmup affordable)."""
    cache_dir = str(tmp_path_factory.mktemp("aot"))
    dsl.reset()
    x = dsl.data(name="x", size=DIM)
    lab = dsl.data(name="label", size=CLASSES)
    out = dsl.fc(input=x, size=CLASSES, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    from paddle_tpu.core.network import Network
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    feeding = {"x": dense_vector(DIM), "label": integer_value(CLASSES)}

    def build_engine():
        pred = ServingPredictor(graph, params, ["out"], feeding,
                                batch_buckets=[1, 2],
                                aot_cache=cache_dir)
        return ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                             queue_depth=32).start(warmup=True)

    engines = [build_engine() for _ in range(2)]
    router = ReplicaRouter([EngineTransport(e) for e in engines],
                           health_poll_ms=25.0).start()
    server = make_router_server(router, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    client = ServingClient(port=server.server_address[1])
    yield {"router": router, "server": server, "client": client,
           "engines": engines}
    server.shutdown()
    server.server_close()
    router.shutdown()


def _spans_settled(tracer, trace_id, names, timeout=5.0):
    """The batcher emits replica/phase spans from the worker thread
    AFTER answering the waiter; give them a beat to land."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = {s["name"] for s in tracer.spans(trace_id)}
        if names <= got:
            return tracer.spans(trace_id)
        time.sleep(0.01)
    return tracer.spans(trace_id)


# ----------------------------------------------------------- propagation
def test_one_trace_id_survives_router_dispatch_over_http(fleet, tracer):
    """client → router HTTP → dispatch → in-process replica → batcher:
    every span of the hop chain carries ONE trace_id, the phase split
    is real child spans, and the parent chain resolves link by link."""
    result = fleet["client"].score(SAMPLE)
    tid = result["provenance"]["trace_id"]
    assert len(tid) == 32 and set(tid) <= HEX
    assert fleet["client"].last_provenance["trace_id"] == tid
    spans = _spans_settled(tracer, tid, {
        "client.request", "router.dispatch", "router.attempt",
        "replica.score", "phase.queue_wait", "phase.compute"})
    by_name = {}
    for s in spans:
        assert s["trace_id"] == tid
        by_name.setdefault(s["name"], []).append(s)
    assert len(by_name["client.request"]) == 1
    root = by_name["client.request"][0]
    assert root["parent_id"] is None
    # the chain: dispatch under the client root (via the X-Trace-Id
    # header), attempt under dispatch, replica.score under the attempt,
    # phases under replica.score
    dispatch = by_name["router.dispatch"][0]
    assert dispatch["parent_id"] == root["span_id"]
    attempt = by_name["router.attempt"][0]
    assert attempt["parent_id"] == dispatch["span_id"]
    score = by_name["replica.score"][0]
    assert score["parent_id"] == attempt["span_id"]
    for phase in ("phase.queue_wait", "phase.pad_overhead",
                  "phase.compute"):
        for s in by_name.get(phase, []):
            assert s["parent_id"] == score["span_id"]
    # the phase children partition the replica span by construction
    phase_ms = sum(s["dur_ms"] for s in spans
                   if s["name"].startswith("phase."))
    assert phase_ms == pytest.approx(score["dur_ms"], rel=1e-6, abs=1e-3)


def test_caller_supplied_context_roots_the_trace(fleet, tracer):
    """A caller already inside a span keeps naming the trace: the
    client HTTP attempt parents under the ambient context, so the
    caller's trace_id is the one the fleet echoes back."""
    with trace.span("caller.batch") as ctx:
        result = fleet["client"].score(SAMPLE)
    assert result["provenance"]["trace_id"] == ctx.trace_id
    reqs = [s for s in tracer.spans(ctx.trace_id)
            if s["name"] == "client.request"]
    assert len(reqs) == 1 and reqs[0]["parent_id"] == ctx.span_id


# ------------------------------------------------- the acceptance drill
def test_failover_trace_reconstructs_client_latency(fleet, tracer,
                                                    tmp_path):
    """One scored request, 2-replica router, induced failover: a single
    trace whose root span wall time lands within 5% of the latency the
    client measured, with the failover visible as sibling attempts —
    and whose JSONL dump passes the TRACE_* artifact schema."""
    # the first dispatch attempt is dropped (failover); the answering
    # batch is delayed 50 ms so the 5% reconstruction bound dwarfs
    # host jitter and the sub-span client overhead
    plan = chaos.FaultPlan(seed=7, faults=[
        {"type": "drop", "site": "route_dispatch", "at": 1},
        {"type": "delay", "site": "serve_batch", "at": 1,
         "seconds": 0.05}])
    with chaos.chaos_plan(plan):
        t0 = time.perf_counter()
        result = fleet["client"].score(SAMPLE)
        measured_ms = 1e3 * (time.perf_counter() - t0)
    prov = result["provenance"]
    assert prov["failovers"] == 1
    tid = prov["trace_id"]
    # phase.decode is the LAST write of the worker's emit sequence:
    # once present, the trace is complete and the dump below races
    # nothing
    spans = _spans_settled(tracer, tid, {
        "client.request", "router.dispatch", "router.attempt",
        "replica.score", "phase.decode"})

    # failover = two sibling attempts under ONE dispatch span: the
    # dropped attempt errored, the answering one ok, on a different
    # replica
    attempts = sorted((s for s in spans if s["name"] == "router.attempt"),
                      key=lambda s: s["ts"])
    assert len(attempts) == 2
    assert len({a["parent_id"] for a in attempts}) == 1
    assert attempts[0]["status"] == "error"
    assert attempts[0]["attrs"]["outcome"] == "failed"
    assert attempts[1]["status"] == "ok"
    assert (attempts[0]["attrs"]["replica"]
            != attempts[1]["attrs"]["replica"])

    # the root span reconstructs the client-observed latency within 5%
    roots = [s for s in spans if s["name"] == "client.request"]
    assert len(roots) == 1 and roots[0]["parent_id"] is None
    root_ms = roots[0]["dur_ms"]
    assert measured_ms >= root_ms  # the span nests inside the measure
    assert abs(measured_ms - root_ms) <= 0.05 * measured_ms, (
        f"root span {root_ms:.2f} ms vs client-measured "
        f"{measured_ms:.2f} ms")

    # the dump is a valid TRACE_* artifact: non-empty spans, monotone
    # file order, every parent ref resolving in-file (PT401 is the
    # judge, not a re-implementation of it)
    path = tracer.dump_jsonl(str(tmp_path / "trace.jsonl"),
                             trace_id=tid)
    import json
    with open(path, encoding="utf-8") as f:
        dumped = [json.loads(line) for line in f]
    assert {s["span_id"] for s in dumped} == {s["span_id"] for s in spans}
    artifact = tmp_path / "TRACE_failover.json"
    artifact.write_text(json.dumps({"spans": dumped}))
    from paddle_tpu.analysis.bench_schema import check_bench_file
    findings = check_bench_file(str(artifact), "TRACE_failover.json")
    assert findings == [], [f.message for f in findings]


# ----------------------------------------------------------------- hedge
class _FakeTransport:
    """Minimal scripted replica (the test_serving_fleet idiom) for the
    hedge-span shape — no jax, deterministic timing."""

    def __init__(self, delay=0.0):
        self.delay = delay

    def start_call(self, kind, sample, deadline_ms, gen_opts):
        p = PendingCall()
        # the attempt context is ambient at start_call; a real
        # transport propagates it onward — the fake only answers
        def finish():
            p.result = {"outputs": {"out": [1.0]}}
            p.event.set()

        if self.delay:
            threading.Timer(self.delay, finish).start()
        else:
            finish()
        return p

    def healthz(self):
        return {"live": True, "ready": True, "draining": False,
                "status": "ok"}

    def begin_drain(self):
        pass

    def drain_wait(self, timeout=60.0):
        pass


def test_hedged_score_appears_as_sibling_hedge_attempt(tracer):
    """A hedge is a SIBLING attempt under the same dispatch span,
    attributed ``hedge=True``; the outrun primary settles later as an
    abandoned attempt of the same trace."""
    slow = _FakeTransport(delay=0.25)
    fast = _FakeTransport()
    router = ReplicaRouter([slow, fast], health_poll_ms=1e6,
                           hedge_ms=20.0)
    router.poll_once()
    router.replicas[1].inflight = 1  # deterministic: slow picked first
    res, prov = router.dispatch(SAMPLE, kind="score")
    assert prov["hedges"] == 1 and prov["replica"] == "r1"
    tid = {s["trace_id"] for s in tracer.spans()
           if s["name"] == "router.dispatch"}.pop()
    # the abandoned primary records when its timer fires (~0.25 s)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        attempts = [s for s in tracer.spans(tid)
                    if s["name"] == "router.attempt"]
        if len(attempts) == 2:
            break
        time.sleep(0.01)
    assert len(attempts) == 2
    assert len({a["parent_id"] for a in attempts}) == 1
    hedge = [a for a in attempts if a["attrs"].get("hedge")]
    primary = [a for a in attempts if not a["attrs"].get("hedge")]
    assert len(hedge) == 1 and hedge[0]["attrs"]["replica"] == "r1"
    assert len(primary) == 1 and primary[0]["attrs"].get("abandoned")


# ------------------------------------------------------------- the echo
def test_typed_errors_echo_trace_id(fleet):
    """A 4xx carries the X-Trace-Id echo into ``error.provenance`` —
    with NO tracer installed, proving the echo contract is not gated
    on recording."""
    assert trace.active() is None
    with pytest.raises(BadRequest) as ei:
        fleet["client"].score("not-a-sample")
    tid = ei.value.provenance["trace_id"]
    assert len(tid) == 32 and set(tid) <= HEX


def test_fenced_standby_503_echoes_trace_id(tmp_path):
    """A fenced standby's refusal still names the trace that refused:
    the 503 carries the echo and the client surfaces it."""
    from paddle_tpu.dist.master import FileStore, RoleLease
    store = FileStore(str(tmp_path / "store"))
    fence = RoleLease(store, "standby", ttl_s=30.0, settle_s=0.0)
    standby = ReplicaRouter([], fence=fence)  # never acquired: fenced
    server = make_router_server(standby, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        client = ServingClient(port=server.server_address[1], retries=0)
        with pytest.raises(Unavailable) as ei:
            client.score(SAMPLE)
        tid = ei.value.provenance["trace_id"]
        assert len(tid) == 32 and set(tid) <= HEX
    finally:
        server.shutdown()
        server.server_close()
        standby._stop.set()


def test_remote_replica_provenance_survives_the_router_hop(fleet):
    """Regression: the replica server now echoes X-Trace-Id, so the
    router's INNER client attaches a partial provenance to the replica
    body — forwarded verbatim it would pre-empt the end client's
    setdefault and eat replica/failover provenance. The transport
    strips it; the end client must still see the router's full
    provenance (plus the trace id) on a remote-replica fleet."""
    from paddle_tpu.serving.router import HTTPTransport
    from paddle_tpu.serving.server import make_server
    rep_srv = make_server(fleet["engines"][0], port=0)
    threading.Thread(target=rep_srv.serve_forever, daemon=True).start()
    router = ReplicaRouter(
        [HTTPTransport("127.0.0.1", rep_srv.server_address[1])],
        health_poll_ms=1e6)
    router.poll_once()
    srv = make_router_server(router, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        client = ServingClient(port=srv.server_address[1])
        res = client.score(SAMPLE)
        prov = res["provenance"]
        assert prov["replica"] == "r0"
        assert prov["failovers"] == 0
        assert len(prov["trace_id"]) == 32 and set(prov["trace_id"]) <= HEX
    finally:
        srv.shutdown()
        srv.server_close()
        router._stop.set()
        rep_srv.shutdown()
        rep_srv.server_close()


# ------------------------------------------------------ training plane
def test_master_rpc_spans_pair_under_one_trace(tracer):
    """The master RPC codec: the trainer-side ``rpc.heartbeat`` span
    and the master-side ``rpc.server.heartbeat`` span share one trace,
    parent-linked through the envelope's ``trace`` field."""
    from paddle_tpu.dist import MasterClient, MasterServer, MasterService
    svc = MasterService()
    server = MasterServer(svc).start()
    try:
        client = MasterClient(server.addr, trainer_id="tr-0",
                              retries=5, retry_delay=0.05)
        client.heartbeat()
        client.close()
    finally:
        server.stop()
    spans = tracer.spans()
    cli = [s for s in spans if s["name"] == "rpc.heartbeat"]
    srv = [s for s in spans if s["name"] == "rpc.server.heartbeat"]
    # a slow 1-core host can time out the FIRST attempt: the client
    # retries (each attempt legitimately records its own span) and the
    # server may still answer the stale attempt late — so BOTH sides
    # can have >1 span. The contract under test is the PAIRING: every
    # server span is parent-linked to exactly one client attempt span
    # within one trace, not the attempt count on either side.
    assert cli and srv
    for s in srv:
        mate = [c for c in cli if c["trace_id"] == s["trace_id"]]
        assert len(mate) == 1, (srv, cli)
        assert s["parent_id"] == mate[0]["span_id"]
