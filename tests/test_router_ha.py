"""Router HA + load-driven autoscaling: the self-operating fleet tier.

The r14 acceptance spine: a warm standby router adopts the replica set
when the active dies (state reconstructs from health polls — adoption
is re-poll + re-arm), the role lease's epoch guard provably FENCES a
partitioned old active (it stops dispatching within one ttl, and its
renewals are refused forever after the takeover), clients re-resolve
across the router endpoints with provenance, and the autoscaler moves
real replica capacity up and down with hysteresis inside
``[min, max]``. The slow+chaos soak at the bottom kills the ACTIVE
router mid-open-loop-load, twice from one seed: the standby answers
within one health interval of the lease lapsing and not one non-shed
request fails, with the fault log reproducing bitwise.
"""

import threading
import time

import numpy as np
import pytest

import jax

from paddle_tpu.config import dsl
from paddle_tpu.core.network import Network
from paddle_tpu.data import dense_vector, integer_value
from paddle_tpu.dist.master import InMemStore, RoleLease
from paddle_tpu.serving import (Autoscaler, EngineTransport,
                                InProcessFleet, Overloaded,
                                ReplicaRouter, RouterHA, ServingClient,
                                ServingEngine, ServingError,
                                ServingPredictor, Unavailable,
                                make_router_server)
from paddle_tpu.testing import chaos

DIM, CLASSES = 8, 4
SAMPLE = ((np.arange(DIM, dtype=float) / DIM).tolist(), 1)


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    """Two warmed in-process replica engines over a shared AOT cache
    (module-scoped: the 1-core host cannot afford per-test warmup).
    Tests build ROUTERS over these per test; none may drain them."""
    cache_dir = str(tmp_path_factory.mktemp("aot"))
    dsl.reset()
    x = dsl.data(name="x", size=DIM)
    lab = dsl.data(name="label", size=CLASSES)
    out = dsl.fc(input=x, size=CLASSES, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    feeding = {"x": dense_vector(DIM), "label": integer_value(CLASSES)}

    def build_engine():
        pred = ServingPredictor(graph, params, ["out"], feeding,
                                batch_buckets=[1, 2],
                                aot_cache=cache_dir)
        return ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                             queue_depth=64).start(warmup=True)

    engs = [build_engine() for _ in range(2)]
    yield {"engines": engs, "build_engine": build_engine}
    for e in engs:
        e.shutdown(drain=False)


def _ha_pair(engines, ttl_s=0.4):
    """An ACTIVE router (holding the role) and a WARM STANDBY (empty,
    fenced, mirroring the active via an injected peer_healthz) over one
    shared role-lease store. Deterministic: no background threads —
    tests drive RouterHA.step() and poll_once() inline."""
    store = InMemStore()
    lease_a = RoleLease(store, "A", ttl_s=ttl_s, settle_s=0.0)
    lease_b = RoleLease(store, "B", ttl_s=ttl_s, settle_s=0.0)
    active = ReplicaRouter([EngineTransport(e)
                            for e in engines["engines"]],
                           fence=lease_a)
    active.poll_once()
    peer_alive = {"up": True}

    def peer_healthz():
        if not peer_alive["up"]:
            raise ConnectionError("active router is dead")
        return active.fleet_health()

    by_id = {f"r{i}": e for i, e in enumerate(engines["engines"])}

    def adopt(snaps):
        return [(s["id"], EngineTransport(by_id[s["id"]]))
                for s in snaps if s["id"] in by_id]

    standby = ReplicaRouter([], fence=lease_b)
    ha_a = RouterHA(active, lease_a)
    ha_b = RouterHA(standby, lease_b, peer_healthz=peer_healthz,
                    adopt=adopt, adopt_after=2)
    assert lease_a.try_acquire()
    return {"active": active, "standby": standby, "ha_a": ha_a,
            "ha_b": ha_b, "lease_a": lease_a, "lease_b": lease_b,
            "peer_alive": peer_alive}


# ------------------------------------------------------------ fencing
def test_standby_is_fenced_until_adoption(engines):
    pair = _ha_pair(engines)
    with pytest.raises(Unavailable) as ei:
        pair["standby"].dispatch(SAMPLE)
    assert "fenced" in str(ei.value)
    assert pair["standby"].metrics.snapshot()["fenced_total"] == 1
    h = pair["standby"].fleet_health()
    assert h["status"] == "fenced" and not h["ready"]
    # the active serves normally, role held
    result, prov = pair["active"].dispatch(SAMPLE)
    assert "outputs" in result
    assert pair["active"].fleet_health()["role_held"] is True


def test_standby_adopts_on_active_death_within_one_interval(engines):
    """Kill the active (stops renewing AND stops answering): after the
    lease lapses, the standby's very next HA step adopts and serves —
    'answers within one health interval' as a deterministic statement.
    Provenance and replica identity carry over (same replica ids)."""
    pair = _ha_pair(engines, ttl_s=0.3)
    ha_b = pair["ha_b"]
    # healthy watch: the standby mirrors the active's replica set
    ha_b.step()
    assert [s["id"] for s in ha_b.last_peer_snapshot] == ["r0", "r1"]
    assert ha_b.adoptions == 0
    # ACTIVE DIES: renewals stop, healthz unreachable
    pair["peer_alive"]["up"] = False
    ha_b.step()  # failure 1
    ha_b.step()  # failure 2 → adopt_after reached, but the lease is
    # still live — takeover is lease-GATED, no split brain
    assert ha_b.adoptions == 0 and not pair["lease_b"].valid()
    time.sleep(0.35)  # the dead active's lease lapses
    t0 = time.monotonic()
    ha_b.step()  # ONE step: acquire + adopt + re-arm
    adopt_ms = 1e3 * (time.monotonic() - t0)
    assert ha_b.adoptions == 1
    assert pair["lease_b"].valid()
    assert pair["lease_b"].epoch == pair["lease_a"].epoch + 1
    result, prov = pair["standby"].dispatch(SAMPLE)
    assert "outputs" in result and prov["replica"] in ("r0", "r1")
    snap = pair["standby"].metrics.snapshot()
    assert snap["adoptions_total"] == 1
    # the takeover itself is sub-interval work (re-poll + re-arm of an
    # in-process fleet is milliseconds; the budget is the 100ms default
    # health interval)
    assert adopt_ms < 1000.0, adopt_ms


@pytest.mark.chaos
def test_partitioned_active_is_fenced_and_epoch_guarded(engines):
    """A seeded partition drops every active-role renewal: the old
    active self-fences within one ttl (dispatch raises Unavailable,
    PROVABLY stopped), the standby takes over with a bumped epoch, and
    even after the partition heals the old active's renew is refused
    (epoch guard) — the r11 zombie-finish protection applied to
    routing."""
    pair = _ha_pair(engines, ttl_s=0.3)
    ha_a, ha_b = pair["ha_a"], pair["ha_b"]
    plan = chaos.FaultPlan(seed=7, faults=[
        {"type": "partition", "site": "lease_renew", "after": 0,
         "count": 1000}])
    with chaos.chaos_plan(plan):
        ha_a.step()  # renewal LOST (dropped), validity keeps ticking
        assert pair["lease_a"].valid()  # not yet fenced...
        time.sleep(0.35)  # ttl lapses with the renewal lost
        assert not pair["lease_a"].valid()
        ha_a.step()  # now fenced: the loop stops renewing entirely
        # (it watches for a chance to RE-acquire instead)
        with pytest.raises(Unavailable) as ei:
            pair["active"].dispatch(SAMPLE)
        assert "fenced" in str(ei.value)
        # standby watches a peer that ANSWERS but is not ready (fenced)
        pair["ha_b"].step()
        pair["ha_b"].step()
        assert ha_b.adoptions == 1  # lease was free: adopted at once
    assert plan.hits("lease_renew") == 1  # fenced holders stop renewing
    # partition healed: the old active's renew hits the epoch guard
    assert not pair["lease_a"].renew()
    assert not pair["lease_a"].valid()
    with pytest.raises(Unavailable):
        pair["active"].dispatch(SAMPLE)
    # the adopted fleet serves
    result, _ = pair["standby"].dispatch(SAMPLE)
    assert "outputs" in result


# ----------------------------------------------------- client endpoints
def test_client_rotates_endpoints_with_provenance(engines):
    """ServingClient(endpoints=[dead, live]) rides its existing backoff
    to the answering endpoint and surfaces it in last_provenance."""
    router = ReplicaRouter([EngineTransport(engines["engines"][0])])
    router.poll_once()
    server = make_router_server(router, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        live = server.server_address[1]
        from paddle_tpu.serving.supervisor import free_port
        dead = free_port()  # nothing listens here
        client = ServingClient(
            endpoints=[f"127.0.0.1:{dead}", f"127.0.0.1:{live}"],
            retries=3, backoff_base_ms=5.0, backoff_seed=0)
        result = client.score(SAMPLE)
        assert "outputs" in result
        assert client.last_provenance["endpoint"] == f"127.0.0.1:{live}"
        assert client.last_provenance["replica"] == "r0"
        assert result["provenance"]["endpoint"] == f"127.0.0.1:{live}"
    finally:
        server.shutdown()


def test_client_rotates_off_fenced_router_on_503(engines):
    """A fenced router's 503 Unavailable makes the client re-resolve to
    the next endpoint — the standby-then-active discovery path."""
    lease = RoleLease(InMemStore(), "X", ttl_s=0.2, settle_s=0.0)
    fenced = ReplicaRouter([EngineTransport(engines["engines"][0])],
                           fence=lease)  # never acquired: fenced
    fenced.poll_once()
    live = ReplicaRouter([EngineTransport(engines["engines"][1])])
    live.poll_once()
    s1 = make_router_server(fenced, port=0)
    s2 = make_router_server(live, port=0)
    for s in (s1, s2):
        threading.Thread(target=s.serve_forever, daemon=True).start()
    try:
        client = ServingClient(
            endpoints=[f"127.0.0.1:{s1.server_address[1]}",
                       f"127.0.0.1:{s2.server_address[1]}"],
            retries=2, backoff_base_ms=5.0, backoff_seed=0)
        result = client.score(SAMPLE)
        assert "outputs" in result
        assert client.last_provenance["endpoint"] == \
            f"127.0.0.1:{s2.server_address[1]}"
    finally:
        s1.shutdown()
        s2.shutdown()


# ----------------------------------------------------------- autoscale
def test_autoscaler_scales_real_in_process_fleet(engines):
    """The autoscaler against a REAL router fleet (InProcessFleet):
    scale-up builds a warmed engine (AOT cache) and the new replica
    takes dispatches; sustained idle scales back down to the floor;
    the trajectory records the whole path and never leaves [min,max]."""
    router = ReplicaRouter([EngineTransport(engines["engines"][0])])
    router.poll_once()
    new_engines = []

    def build():
        e = engines["build_engine"]()
        new_engines.append(e)
        return EngineTransport(e)

    fleet = InProcessFleet(router, build)
    sc = Autoscaler(fleet, min_replicas=1, max_replicas=3,
                    up_backlog_ms=50.0, down_backlog_ms=5.0,
                    sustain_up_s=0.2, sustain_down_s=0.2,
                    cooldown_s=0.0)
    try:
        now = 0.0
        while fleet.replica_count() < 3 and now < 20.0:
            sc.observe(backlog_ms=200.0, now=now)
            now += 0.3
        assert fleet.replica_count() == 3
        sc.observe(backlog_ms=200.0, now=now)  # at max: clamped
        assert fleet.replica_count() == 3
        # the grown fleet actually serves on its new replicas
        seen = set()
        for _ in range(12):
            _, prov = router.dispatch(SAMPLE)
            seen.add(prov["replica"])
        assert len(seen) >= 2
        # sustained idle: back down to the floor (draining, zero drops)
        guard = 0
        while fleet.replica_count() > 1 and guard < 100:
            sc.observe(backlog_ms=0.0, now=now)
            now += 0.3
            guard += 1
        assert fleet.replica_count() == 1
        counts = [n for _, n in sc.trajectory]
        assert max(counts) == 3 and counts[-1] == 1
        assert all(1 <= n <= 3 for n in counts)
        snap = router.metrics.snapshot()
        assert snap["scale_up_total"] == 2
        assert snap["scale_down_total"] == 2
        # the survivor still serves
        result, _ = router.dispatch(SAMPLE)
        assert "outputs" in result
    finally:
        for e in new_engines:
            e.shutdown(drain=False)


# ------------------------------------------------------------- the soak
@pytest.mark.slow
@pytest.mark.chaos
def test_kill_active_router_under_open_loop_load_soak(tmp_path,
                                                      monkeypatch):
    """THE acceptance drill: open-loop load through HA client endpoints
    while the ACTIVE router process is killed mid-run (listener torn
    down, renewals stop — the in-process analogue of a SIGKILL). The
    warm standby adopts once the lease lapses and answers within one
    health interval; summed across BOTH seeded rounds, zero non-shed
    requests fail; and the chaos fault log reproduces bitwise from the
    seed.

    r15: the soak runs with a flight recorder ARMED and dumps through
    the ``$PADDLE_TPU_FLIGHT_DIR`` path per round; the blackbox merge
    then names the takeover sequence — renewals dropped (chaos fires)
    → old active FENCED → stale lease adopted → HA takeover → first
    standby-served answer — from the dumps alone, no seed re-run."""
    import jax as _jax  # noqa: F401
    from paddle_tpu.obs import flight
    flight_dir = tmp_path / "flight"
    monkeypatch.setenv(flight.ENV_DIR, str(flight_dir))
    dsl.reset()
    x = dsl.data(name="x", size=DIM)
    lab = dsl.data(name="label", size=CLASSES)
    out = dsl.fc(input=x, size=CLASSES, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    feeding = {"x": dense_vector(DIM), "label": integer_value(CLASSES)}
    cache_dir = str(tmp_path / "aot")

    def build_engine():
        pred = ServingPredictor(graph, params, ["out"], feeding,
                                batch_buckets=[1, 2],
                                aot_cache=cache_dir)
        return ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                             queue_depth=64).start(warmup=True)

    def run_round(seed, tag):
        # one recorder per "fleet" (this in-process pair is one
        # process; a real fleet dumps one file per process) — the
        # service name keys the per-round dump file. Armed under
        # try/finally: a failing round must not leak the installed
        # recorder into every later test in this process.
        flight.install(flight.FlightRecorder(f"soak{tag}"))
        try:
            return _run_round(seed, tag)
        finally:
            flight.install(None)

    def _run_round(seed, tag):
        engs = [build_engine() for _ in range(2)]
        store = InMemStore()
        ttl = 0.4
        interval_ms = 100.0
        lease_a = RoleLease(store, "A", ttl_s=ttl, settle_s=0.0)
        lease_b = RoleLease(store, "B", ttl_s=ttl, settle_s=0.0)
        active = ReplicaRouter([EngineTransport(e) for e in engs],
                               fence=lease_a, health_poll_ms=25.0)
        standby = ReplicaRouter([], fence=lease_b, health_poll_ms=25.0)
        srv_a = make_router_server(active, port=0)
        srv_b = make_router_server(standby, port=0)
        for s in (srv_a, srv_b):
            threading.Thread(target=s.serve_forever,
                             daemon=True).start()
        by_id = {f"r{i}": e for i, e in enumerate(engs)}

        def peer_healthz():
            import http.client
            import json as _json
            conn = http.client.HTTPConnection(
                "127.0.0.1", srv_a.server_address[1], timeout=1.0)
            try:
                conn.request("GET", "/healthz")
                return _json.loads(conn.getresponse().read())
            finally:
                conn.close()

        def adopt(snaps):
            return [(s["id"], EngineTransport(by_id[s["id"]]))
                    for s in snaps if s["id"] in by_id]

        assert lease_a.try_acquire()
        active.start()
        standby.start()
        ha_a = RouterHA(active, lease_a,
                        interval_ms=interval_ms).start()
        ha_b = RouterHA(standby, lease_b, peer_healthz=peer_healthz,
                        adopt=adopt, adopt_after=2,
                        interval_ms=interval_ms).start()
        plan = chaos.FaultPlan(seed=seed, faults=[
            # the seeded kill trigger: from the Nth renewal on, EVERY
            # renewal of holder A — and only A's — is dropped (the
            # standby's own renewals after adoption must sail through);
            # the harness tears A's listener down when it observes the
            # first drop. A silenced, unreachable active = the kill.
            {"type": "partition", "site": "lease_renew", "after": 4,
             "count": 100000, "match": {"holder": "A"}}])
        n_requests, interval_s = 40, 0.05
        counts = {"ok": 0, "shed": 0, "failed": 0}
        lock = threading.Lock()
        endpoints = [f"127.0.0.1:{srv_a.server_address[1]}",
                     f"127.0.0.1:{srv_b.server_address[1]}"]
        killed = {"t": None}
        answered_by = []

        def kill_watch():
            while plan.hits("lease_renew") < 5:
                time.sleep(0.01)
            # the active router "process" dies: accept loop stopped AND
            # the listening socket CLOSED — a real process death frees
            # the port; shutdown() alone would leave the kernel backlog
            # swallowing new connections into a black hole
            killed["t"] = time.monotonic()
            srv_a.shutdown()
            srv_a.server_close()

        def one(i):
            client = ServingClient(endpoints=list(endpoints),
                                   timeout=10.0,
                                   retries=8, backoff_base_ms=20.0,
                                   backoff_seed=seed * 1000 + i)
            try:
                client.score(SAMPLE)
                key = "ok"
                with lock:
                    answered_by.append(
                        (client.last_provenance or {}).get("endpoint"))
            except Unavailable:
                key = "failed"  # outage, not backpressure
            except Overloaded:
                key = "shed"
            except ServingError:
                key = "failed"
            except OSError:
                key = "failed"
            with lock:
                counts[key] += 1

        watcher = threading.Thread(target=kill_watch, daemon=True)
        threads = []
        with chaos.chaos_plan(plan):
            watcher.start()
            t0 = time.monotonic()
            for i in range(n_requests):
                target = t0 + i * interval_s
                d = target - time.monotonic()
                if d > 0:
                    time.sleep(d)
                th = threading.Thread(target=one, args=(i,))
                th.start()
                threads.append(th)
            for th in threads:
                th.join(60.0)
            watcher.join(10.0)
            # the standby adopted within one health interval of the
            # lease lapsing (kill time + ttl + one interval + slack)
            deadline = time.monotonic() + 10.0
            while ha_b.adoptions == 0 and time.monotonic() < deadline:
                time.sleep(0.02)
        assert killed["t"] is not None, "the kill never fired"
        assert ha_b.adoptions == 1
        adoption_lag = ha_b.adopted_at - killed["t"]
        assert adoption_lag < ttl + 3 * (interval_ms / 1e3) + 0.5, \
            f"standby took {adoption_lag:.2f}s to adopt"
        # both endpoints actually answered traffic across the kill
        # (exact compare — a port-digit suffix match could credit the
        # active, e.g. ":18080".endswith("8080"))
        standby_ep = f"127.0.0.1:{srv_b.server_address[1]}"
        assert any(e == standby_ep for e in answered_by), \
            "standby never answered"
        ha_a.shutdown(release=False)
        ha_b.shutdown(release=False)
        srv_b.shutdown()
        active._stop.set()
        standby._stop.set()
        for e in engs:
            e.shutdown(drain=False)
        # the dump path the acceptance requires: through the env-dir
        # naming (what SIGTERM/atexit/worker-fatal use), not an
        # explicit path
        dump = flight.dump_now()
        assert dump is not None and dump.startswith(str(flight_dir))
        return counts, list(plan.log)

    c1, log1 = run_round(11, "a")
    c2, log2 = run_round(11, "b")
    # zero failed non-shed SUMMED across rounds — a failing round
    # cannot hide behind a better sibling
    assert c1["failed"] + c2["failed"] == 0, (c1, c2)
    assert c1["ok"] + c2["ok"] > 0
    # the seeded fault SCHEDULE reproduces: the kill lands at exactly
    # the same hit in both rounds, and every fired fault is the
    # targeted partition (how MANY drops land before the active fences
    # is wall-clock — the schedule, not the count, is the seed's
    # contract)
    assert log1[0] == log2[0] == ("lease_renew", 5, "partition")
    for log in (log1, log2):
        assert all(site == "lease_renew" and kind == "partition"
                   for site, _, kind in log)

    # ---- the postmortem reads off the black boxes alone -------------
    # merge BOTH rounds' dumps fleet-wide, then name each round's
    # takeover sequence by event order — no seed re-run, no in-process
    # state: everything below comes from the JSONL dumps
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import blackbox
    merged = blackbox.merge_dir(str(flight_dir))
    assert merged, "no flight events survived the soak"
    for tag in ("a", "b"):
        ev = [e for e in merged if e["service"] == f"soak{tag}"]

        def first(name, **match):
            for i, e in enumerate(ev):
                if e["event"] == name and all(
                        e.get(k) == v for k, v in match.items()):
                    return i
            raise AssertionError(
                f"round {tag}: no {name} {match} in the black box: "
                + blackbox.format_timeline(ev))

        i_drop = first("chaos_fire", site="lease_renew")
        i_fenced = first("role_fenced", holder="A")
        i_adopt = first("role_acquire", holder="B",
                        took_over_stale=True)
        i_takeover = first("ha_takeover", holder="B")
        i_answer = first("first_answer_after_takeover")
        # lease expiry (renewals dropped, old active fenced) →
        # adoption (stale lease claimed, fleet adopted) → first
        # standby answer: the whole story, in order, from the dumps
        assert i_drop < i_adopt <= i_takeover < i_answer, (
            blackbox.format_timeline(ev))
        assert i_fenced > i_drop, blackbox.format_timeline(ev)
        adopt_rec = ev[i_adopt]
        takeover_rec = ev[i_takeover]
        assert takeover_rec["epoch"] == adopt_rec["epoch"]
    # the human-readable timeline carries the same story
    text = blackbox.format_timeline(merged)
    for name in ("role_fenced", "role_acquire", "ha_takeover",
                 "first_answer_after_takeover"):
        assert name in text
