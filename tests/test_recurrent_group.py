"""recurrent_group tests — the analogue of the reference's
``test_RecurrentGradientMachine.cpp`` (a recurrent_group-built RNN must
equal its flat builtin twin, ``sequence_rnn.conf`` vs
``sequence_nest_rnn.conf``)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.core.network import Network


def _seq_feed(rng, B, T, D, lens):
    x = rng.randn(B, T, D).astype(np.float32)
    mask = np.zeros((B, T), np.float32)
    for b, n in enumerate(lens):
        mask[b, :n] = 1.0
    x = x * mask[:, :, None]
    return Argument(value=jnp.asarray(x), mask=jnp.asarray(mask))


def test_group_rnn_equals_builtin_recurrent():
    rng = np.random.RandomState(0)
    B, T, D = 3, 5, 4
    feed_arg = _seq_feed(rng, B, T, D, [5, 3, 1])

    # builtin: out_t = tanh(x_t + h_{t-1} W)
    dsl.reset()
    x = dsl.data("x", size=D, is_sequence=True)
    r = dsl.recurrent(x, act="tanh", name="rnn", bias_attr=False)
    net_flat = Network(dsl.current_graph(), outputs=["rnn"])
    params_flat = net_flat.init_params(jax.random.PRNGKey(1))

    # group: h_t = tanh(x_t + fc(h_{t-1}))  (same math, traced step net)
    dsl.reset()
    x2 = dsl.data("x", size=D, is_sequence=True)

    def step(xt):
        m = dsl.memory(name="h", size=D)
        proj = dsl.fc(m, size=D, act="linear", name="proj", bias_attr=False)
        return dsl.addto([xt, proj], act="tanh", name="h")

    out = dsl.recurrent_group(step, [x2], name="grp")
    net_grp = Network(dsl.current_graph(), outputs=[out.name])
    params_grp = net_grp.init_params(jax.random.PRNGKey(2))
    assert "_proj.w0" in params_grp  # hoisted under its sub-layer name
    params_grp = dict(params_grp)
    params_grp["_proj.w0"] = params_flat["_rnn.w0"]

    y_flat = net_flat.apply(params_flat, {"x": feed_arg})["rnn"].value
    y_grp = net_grp.apply(params_grp, {"x": feed_arg})[out.name].value
    np.testing.assert_allclose(np.asarray(y_flat), np.asarray(y_grp),
                               rtol=1e-5, atol=1e-6)


def test_group_grad_flows_and_respects_mask():
    rng = np.random.RandomState(1)
    B, T, D = 2, 4, 3
    feed_arg = _seq_feed(rng, B, T, D, [4, 2])
    dsl.reset()
    x = dsl.data("x", size=D, is_sequence=True)

    def step(xt):
        m = dsl.memory(name="h", size=D)
        proj = dsl.fc(m, size=D, act="linear", name="proj", bias_attr=False)
        return dsl.addto([xt, proj], act="tanh", name="h")

    out = dsl.recurrent_group(step, [x], name="grp")
    net = Network(dsl.current_graph(), outputs=[out.name])
    params = net.init_params(jax.random.PRNGKey(0))

    def loss(p):
        y = net.apply(p, {"x": feed_arg})[out.name].value
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["_proj.w0"]).sum()) > 0
    # padded positions emit zeros
    y = net.apply(params, {"x": feed_arg})[out.name].value
    np.testing.assert_allclose(np.asarray(y[1, 2:]), 0.0, atol=1e-7)


def test_group_static_input_and_boot():
    rng = np.random.RandomState(2)
    B, T, D = 2, 3, 4
    feed_arg = _seq_feed(rng, B, T, D, [3, 3])
    ctxv = rng.randn(B, D).astype(np.float32)
    dsl.reset()
    x = dsl.data("x", size=D, is_sequence=True)
    c = dsl.data("c", size=D)
    boot = dsl.fc(c, size=D, act="linear", name="boot", bias_attr=False)

    def step(xt, cs):
        m = dsl.memory(name="h", size=D, boot_layer=boot)
        s = dsl.addto([xt, cs, m], act="tanh", name="h")
        return s

    out = dsl.recurrent_group(step, [x, dsl.StaticInput(c)], name="grp")
    net = Network(dsl.current_graph(), outputs=[out.name])
    params = net.init_params(jax.random.PRNGKey(0))
    outs = net.apply(params, {"x": feed_arg, "c": Argument(value=jnp.asarray(ctxv))})
    y = np.asarray(outs[out.name].value)
    # manual reference
    W = np.asarray(params["_boot.w0"])
    h = ctxv @ W
    xv = np.asarray(feed_arg.value)
    for t in range(T):
        h = np.tanh(xv[:, t] + ctxv + h)
        np.testing.assert_allclose(y[:, t], h, rtol=1e-5, atol=1e-6)


def test_group_multiple_outputs():
    rng = np.random.RandomState(3)
    B, T, D = 2, 3, 4
    feed_arg = _seq_feed(rng, B, T, D, [3, 2])
    dsl.reset()
    x = dsl.data("x", size=D, is_sequence=True)

    def step(xt):
        m = dsl.memory(name="h", size=D)
        h = dsl.addto([xt, m], act="tanh", name="h")
        sq = dsl.slope_intercept(h, slope=2.0, name="sq")
        return h, sq

    h_out, sq_out = dsl.recurrent_group(step, [x], name="grp")
    net = Network(dsl.current_graph(), outputs=[h_out.name, sq_out.name])
    params = net.init_params(jax.random.PRNGKey(0))
    outs = net.apply(params, {"x": feed_arg})
    np.testing.assert_allclose(np.asarray(outs[sq_out.name].value),
                               2.0 * np.asarray(outs[h_out.name].value),
                               rtol=1e-6)


def test_memory_outside_group_raises():
    dsl.reset()
    with pytest.raises(RuntimeError):
        dsl.memory(name="h", size=3)
