"""Pipeline parallelism through the TRAINING loop (`--parallel_nn`):
the GPipe schedule runs forward+backward+optimizer-update inside
`SGD._train_step` with loss-curve parity vs the unpipelined step,
composes with ZeRO-1, and checkpoints cross pipeline on/off both ways.

Closure: the parity matrix below MUST cover ≥2 stage counts plus an
uneven (heterogeneous) split — enforced by `test_parity_matrix_closure`
so a future stage-count addition cannot silently drop a layout."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.optim import Adam, Momentum
from paddle_tpu.parallel import create_mesh
from paddle_tpu.trainer import SGD, events

WIDTH, CLASSES, B = 12, 3, 16

# (stage_count, layers_per_stage list) — uneven rows take the
# heterogeneous (lax.switch, replicated-params) path
PARITY_MATRIX = [
    ("s2", [1, 1]),
    ("s4", [1, 1, 1, 1]),
    ("s2_uneven", [2, 1]),
]


def _build(mesh, split, opt=None, seed=0):
    dsl.reset()
    x = dsl.data(name="x", size=WIDTH)
    lbl = dsl.data(name="label", size=CLASSES)
    h = x
    for s, n_layers in enumerate(split):
        for j in range(n_layers):
            h = dsl.fc(input=h, size=WIDTH, act="tanh", name=f"blk{s}_{j}",
                       layer_attr={"device": s})
    out = dsl.fc(input=h, size=CLASSES, act="softmax", name="out")
    cost = dsl.classification_cost(input=out, label=lbl)
    return SGD(cost=cost,
               update_equation=opt or Adam(learning_rate=3e-3),
               mesh=mesh, seed=seed)


def _reader():
    rng = np.random.RandomState(7)
    X = rng.randn(2 * B, WIDTH).astype(np.float32)
    W = rng.randn(WIDTH, CLASSES)
    Y = np.argmax(X @ W, axis=1).astype(np.int32)

    def reader():
        for i in range(0, 2 * B, B):
            yield {"x": Argument(value=jnp.asarray(X[i:i + B])),
                   "label": Argument(value=jnp.asarray(Y[i:i + B]))}

    return reader


def _train(trainer, reader, passes=2, **kw):
    costs = []
    trainer.train(reader, num_passes=passes,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, events.EndIteration) else None, **kw)
    return costs


def test_parity_matrix_closure():
    splits = [s for _, s in PARITY_MATRIX]
    assert len({len(s) for s in splits}) >= 2, "need >= 2 stage counts"
    assert any(len(set(s)) > 1 for s in splits), "need an uneven split"


@pytest.mark.parametrize("tag,split", PARITY_MATRIX,
                         ids=[t for t, _ in PARITY_MATRIX])
def test_pipelined_training_matches_unpipelined(tag, split):
    """Loss-curve parity over two passes: the pipelined step (DP x PP
    mesh) reproduces the unpipelined run's costs to float tolerance —
    full-batch denominators, one optimizer application."""
    reader = _reader()
    S = len(split)
    tr_pipe = _build(create_mesh(n_data=2, n_pipe=S), split)
    cs_pipe = _train(tr_pipe, reader, pipeline=True)
    assert tr_pipe._pipe is not None, "pipeline stood down unexpectedly"
    assert tr_pipe._pipe.identical == (len(set(split)) == 1)
    tr_ref = _build(None, split)
    cs_ref = _train(tr_ref, reader)
    np.testing.assert_allclose(cs_pipe, cs_ref, rtol=2e-5, atol=2e-6)
    # trained parameters agree too (checkpoint view is flat both ways)
    flat = tr_pipe._params_for_save()
    for k, v in tr_ref.params.items():
        np.testing.assert_allclose(np.asarray(flat[k]), np.asarray(v),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_stacked_params_shard_one_stage_per_slot():
    """The fast path stores body params stage-stacked with the leading
    dim over the pipe axis: each mesh slot holds ONE stage's parameters
    (and optimizer slots) — 1/S of the body state per device."""
    tr = _build(create_mesh(n_data=2, n_pipe=4), [1, 1, 1, 1])
    tr.train(_reader(), num_passes=1, pipeline=True)
    stacked = tr.params["_blk0_0.w0"]
    assert stacked.shape == (4, WIDTH, WIDTH)
    assert "pipe" in str(stacked.sharding.spec), stacked.sharding
    mom = tr.opt_state["slots"]["_blk0_0.w0"]["mom"]
    assert mom.shape == (4, WIDTH, WIDTH)
    assert "pipe" in str(mom.sharding.spec), mom.sharding
    # per-stage names are absorbed into the stack
    assert "_blk1_0.w0" not in tr.params
    # and the step breakdown carries the bubble accounting
    s = tr.step_breakdown()
    assert s["pipeline_stages"] == 4
    assert s["pipeline_bubble_frac"] == pytest.approx(3 / 7)
    assert len(s["pipeline_bubble_frac_per_stage"]) == 4


def test_pipeline_composes_with_zero1():
    """pipeline=True + zero1=True: stacked body slots stay stage-sharded
    (excluded from the ZeRO-1 plan via the pipe rules), the head's slots
    partition over the data axis, and the result still matches the plain
    replicated run."""
    reader = _reader()
    tr = _build(create_mesh(n_data=4, n_pipe=2), [1, 1])
    cs = _train(tr, reader, pipeline=True, zero1=True)
    assert tr._pipe is not None and tr._zero1 is not None
    # stacked keys excluded from the ZeRO-1 plan; head params planned
    assert not any(k in tr._zero1.plan for k in tr._pipe.stacked_map)
    assert "_out.w0" in tr._zero1.plan
    tr_ref = _build(None, [1, 1])
    cs_ref = _train(tr_ref, reader)
    np.testing.assert_allclose(cs, cs_ref, rtol=2e-5, atol=2e-6)


def test_checkpoint_crosses_pipeline_on_off_both_ways(tmp_path):
    """A pipelined run's checkpoint resumes unpipelined and vice versa:
    the on-disk format is always the flat per-stage one, restacked on
    load when the pipeline is active."""
    from paddle_tpu.dist.checkpoint import Checkpointer
    reader = _reader()
    tr1 = _build(create_mesh(n_data=2, n_pipe=2), [1, 1])
    _train(tr1, reader, pipeline=True)
    Checkpointer(str(tmp_path)).save(
        tr1._params_for_save, tr1._opt_state_for_save,
        pass_id=0, end_of_pass=True)
    flat1 = {k: np.asarray(v) for k, v in tr1._params_for_save().items()}

    # pipelined -> unpipelined
    tr2 = _build(None, [1, 1])
    params, opt_flat, _ = Checkpointer(str(tmp_path)).restore()
    tr2.load_state(params, opt_flat)
    for k, v in tr2.params.items():
        np.testing.assert_allclose(np.asarray(v), flat1[k], err_msg=k)

    # unpipelined (flat format) -> pipelined: restack on load
    tr3 = _build(create_mesh(n_data=2, n_pipe=2), [1, 1])
    assert tr3.enable_pipeline()
    params, opt_flat, _ = Checkpointer(str(tmp_path)).restore()
    tr3.load_state(params, opt_flat)
    flat3 = tr3._params_for_save()
    for k in flat1:
        np.testing.assert_allclose(np.asarray(flat3[k]), flat1[k],
                                   err_msg=k)
    # both resumed runs continue with identical losses
    c2 = _train(tr2, reader, passes=1)
    c3 = _train(tr3, reader, passes=1)
    np.testing.assert_allclose(c2, c3, rtol=2e-5, atol=2e-6)


def test_pipeline_stands_down_cleanly():
    """No device attrs / no pipe axis: enable_pipeline warns and returns
    False; training proceeds unpipelined (the --parallel_nn contract)."""
    dsl.reset()
    x = dsl.data(name="x", size=WIDTH)
    lbl = dsl.data(name="label", size=CLASSES)
    out = dsl.fc(input=x, size=CLASSES, act="softmax", name="out")
    cost = dsl.classification_cost(input=out, label=lbl)
    tr = SGD(cost=cost, update_equation=Momentum(learning_rate=0.1),
             mesh=create_mesh(n_data=2, n_pipe=2))
    assert tr.enable_pipeline() is False  # no device attrs
    assert tr._pipe is None

    # device attrs but a mesh with no pipe axis
    tr2 = _build(create_mesh(n_data=2), [1, 1])
    assert tr2.enable_pipeline() is False
    cs = _train(tr2, _reader(), passes=1, pipeline=True)  # still trains
    assert np.isfinite(cs).all()

    # stage count != pipe-axis width
    tr3 = _build(create_mesh(n_data=2, n_pipe=4), [1, 1])
    assert tr3.enable_pipeline() is False


def test_parallel_nn_cli_trains_with_parity(tmp_path, capsys):
    """A reference-style config with per-layer device attrs trains
    through `trainer/cli.py --parallel_nn` and its final pass matches the
    unflagged run (acceptance criterion of ISSUE r08)."""
    cfg = tmp_path / "pipe_cfg.py"
    cfg.write_text("""
import numpy as np
import jax.numpy as jnp
from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.optim import Momentum

x = dsl.data(name="x", size=16)
lbl = dsl.data(name="label", size=4)
h = x
for s in range(2):
    h = dsl.fc(input=h, size=16, act="tanh", name=f"blk{s}",
               layer_attr={"device": s})
out = dsl.fc(input=h, size=4, act="softmax", name="out")
cost = dsl.classification_cost(input=out, label=lbl)
optimizer = Momentum(learning_rate=0.1, momentum=0.9)
_rng = np.random.RandomState(0)
_X = _rng.randn(32, 16).astype(np.float32)
_W = _rng.randn(16, 4)
_Y = np.argmax(_X @ _W, axis=1).astype(np.int32)
def train_reader():
    for i in (0, 16):
        yield {"x": Argument(value=jnp.asarray(_X[i:i+16])),
               "label": Argument(value=jnp.asarray(_Y[i:i+16]))}
""")
    from paddle_tpu.trainer import cli

    def final_err(argv):
        rc = cli.main(argv)
        assert rc == 0
        out = capsys.readouterr().out
        last = [ln for ln in out.splitlines() if ln.startswith("Pass 2")][0]
        return float(last.split("classification_error=")[1].split()[0])

    base = ["--config", str(cfg), "--job", "train", "--num_passes", "3"]
    err_pipe = final_err(base + ["--parallel_nn",
                                 "--pipeline_microbatches", "4"])
    err_ref = final_err(base)
    assert err_pipe == pytest.approx(err_ref, abs=1e-6)


def test_dsl_pipeline_stage_scope():
    """`with dsl.pipeline_stage(s):` stamps device attrs without
    per-layer spelling; explicit attrs win; data layers are exempt; the
    result derives the same stages as the explicit form."""
    from paddle_tpu.parallel.pipeline import split_pipeline_graph
    dsl.reset()
    x = dsl.data(name="x", size=8)
    lbl = dsl.data(name="label", size=2)
    with dsl.pipeline_stage(0):
        h = dsl.fc(input=x, size=8, act="tanh", name="a0")
        h = dsl.fc(input=h, size=8, act="tanh", name="a1")
    with dsl.pipeline_stage(1):
        h = dsl.fc(input=h, size=8, act="tanh", name="b0",
                   layer_attr={"device": 1})  # explicit agrees
    out = dsl.fc(input=h, size=2, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lbl, name="cost")
    g = dsl.current_graph()
    assert g.layers["a0"].attrs["device"] == 0
    assert g.layers["b0"].attrs["device"] == 1
    assert "device" not in g.layers["x"].attrs
    assert g.layers["out"].attrs.get("device") is None
    stages, head = split_pipeline_graph(g)
    assert stages == [["a0", "a1"], ["b0"]]
    assert head == ["out", "cost"]
    dsl.reset()  # scope must not leak
    assert dsl._DEVICE_SCOPE is None


def test_pipeline_microbatch_gcd_fallback():
    """A batch the configured M doesn't divide scans fewer microbatches
    for that shape instead of crashing (same contract as
    grad_accum_steps' tail-batch handling)."""
    tr = _build(create_mesh(n_data=1, n_pipe=2), [1, 1])
    rng = np.random.RandomState(3)

    def reader():
        for b in (12, 10):  # second batch: 10 % 4 != 0 -> gcd(4,10)=2
            yield {"x": Argument(value=jnp.asarray(
                rng.randn(b, WIDTH).astype(np.float32))),
                "label": Argument(value=jnp.asarray(
                    rng.randint(0, CLASSES, b).astype(np.int32)))}

    cs = _train(tr, reader, passes=1, pipeline={"microbatches": 4})
    assert len(cs) == 2 and np.isfinite(cs).all()


def test_pipeline_composes_with_seq_parallel_head():
    """A (data, seq, pipe) mesh — no fsdp — trains a device-attr-staged
    body with a ring seq-parallel attention HEAD gradient-exact vs the
    unsharded run: the pipeline's shard_map leaves the seq axis
    unmentioned (replicated across it) while the head's attention runs
    its own ring schedule over seq. Pins the create_mesh composition
    form the r17 relaxation opened (previously seq+pipe raised)."""
    W, T, B_ = 8, 4, 8

    def model():
        dsl.reset()
        x = dsl.data(name="x", size=W)
        s = dsl.data(name="s", size=W, is_sequence=True)
        lbl = dsl.data(name="label", size=CLASSES)
        h = dsl.fc(input=x, size=W, act="tanh", name="sp0",
                   layer_attr={"device": 0})
        h = dsl.fc(input=h, size=W, act="tanh", name="sp1",
                   layer_attr={"device": 1})
        att = dsl.multi_head_attention(s, num_heads=2,
                                       seq_parallel="ring", name="satt")
        pooled = dsl.pooling(input=att, pooling_type="avg", name="spool")
        comb = dsl.fc(input=[h, pooled], size=W, act="tanh", name="scmb")
        out = dsl.fc(input=comb, size=CLASSES, act="softmax", name="sout")
        return dsl.classification_cost(input=out, label=lbl)

    rng = np.random.RandomState(9)
    X = rng.randn(2 * B_, W).astype(np.float32)
    S = rng.randn(2 * B_, T, W).astype(np.float32)
    Y = rng.randint(0, CLASSES, 2 * B_).astype(np.int32)

    def reader():
        for i in range(0, 2 * B_, B_):
            yield {"x": Argument(value=jnp.asarray(X[i:i + B_])),
                   "s": Argument(value=jnp.asarray(S[i:i + B_]),
                                 mask=jnp.ones((B_, T), jnp.float32)),
                   "label": Argument(value=jnp.asarray(Y[i:i + B_]))}

    def run(mesh, pipeline):
        tr = SGD(cost=model(), update_equation=Adam(learning_rate=3e-3),
                 mesh=mesh, seed=4)
        tr.train(reader, num_passes=2, pipeline=pipeline)
        return tr

    base = run(None, None)
    mesh = create_mesh(n_data=2, n_seq=2, n_pipe=2)
    assert tuple(mesh.axis_names) == ("data", "seq", "pipe")
    tr = run(mesh, True)
    assert tr._pipe is not None and tr._pipe.S == 2
    got = tr._params_for_save()
    for k in base.params:
        np.testing.assert_allclose(np.asarray(base.params[k]),
                                   np.asarray(got[k]),
                                   rtol=0, atol=1e-7, err_msg=k)
