"""Tests for the utils subsystem (timers, error context, logging) —
covering the Stat.h / CustomStackTrace behaviors of ``paddle/utils``."""

import time

import jax.numpy as jnp
import pytest

from paddle_tpu.utils import (LayerStackError, StatRegistry,
                              current_layer_stack, global_stat, layer_scope,
                              timer, timer_guard)


def test_timer_accumulates():
    reg = StatRegistry("test")
    for _ in range(3):
        with timer("scope_a", reg):
            time.sleep(0.002)
    s = reg.get("scope_a")
    assert s.count == 3
    assert s.total >= 0.006
    assert s.max >= s.avg >= s.min > 0
    status = reg.status(reset=True)
    assert "scope_a" in status
    assert reg.get("scope_a").count == 0  # reset worked


def test_timer_guard_decorator():
    reg = StatRegistry("test")

    @timer_guard("fn", reg)
    def f(x):
        return x + 1

    assert f(1) == 2
    assert reg.get("fn").count == 1


def test_timer_disabled():
    reg = StatRegistry("test")
    reg.enabled = False
    with timer("x", reg):
        pass
    assert reg.get("x").count == 0


def test_layer_scope_error_chain():
    with pytest.raises(LayerStackError) as ei:
        with layer_scope("fc1"):
            with layer_scope("fc2"):
                raise ValueError("boom")
    assert ei.value.chain == ["fc1", "fc2"]
    assert "fc1 -> fc2" in str(ei.value)
    assert current_layer_stack() == []  # fully popped


def test_layer_scope_clean_exit():
    with layer_scope("a"):
        assert current_layer_stack() == ["a"]
    assert current_layer_stack() == []


def test_network_error_carries_layer_chain():
    """A bad feed shape inside a layer impl should name the failing layer."""
    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.core.network import Network

    dsl.reset()
    d = dsl.data("x", size=4)
    h = dsl.fc(input=d, size=8)
    net = Network(dsl.current_graph(), outputs=[h.name])
    import jax
    params = net.init_params(jax.random.PRNGKey(0))
    bad = {"x": Argument(value=jnp.ones((2, 5)))}  # wrong width
    with pytest.raises(LayerStackError) as ei:
        net.apply(params, bad)
    assert ei.value.chain[-1] == h.name


def test_trainer_log_period_and_param_stats(caplog):
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.data import DataFeeder, dense_vector, integer_value
    from paddle_tpu.optim import Momentum
    from paddle_tpu.trainer import SGD

    dsl.reset()
    x = dsl.data("x", size=4)
    y = dsl.data("y", size=2)
    h = dsl.fc(input=x, size=2, act="softmax")
    cost = dsl.classification_cost(input=h, label=y)
    t = SGD(cost=cost, update_equation=Momentum(learning_rate=0.1))

    rng = np.random.RandomState(0)
    data = [(rng.randn(4).astype("float32"), int(rng.randint(2)))
            for _ in range(8)]
    feeder = DataFeeder({"x": dense_vector(4), "y": integer_value(2)})

    def reader():
        yield data[:4]
        yield data[4:]

    global_stat.reset()
    # the paddle_tpu logger is non-propagating (it owns its glog-format
    # stderr handler), so hook the capture handler onto it directly
    import logging
    plogger = logging.getLogger("paddle_tpu")
    plogger.addHandler(caplog.handler)
    try:
        t.train(reader, feeder=feeder, num_passes=1, log_period=1)
    finally:
        plogger.removeHandler(caplog.handler)
    text = caplog.text
    assert "Cost=" in text and "classification_error=" in text
    assert "trainBatch" in text  # the StatSet dump ran and was formatted
    stats = t.parameter_stats()
    assert any(v["size"] > 0 for v in stats.values())


def test_show_pb_prints_serialized_model_config(tmp_path):
    """utils/show_pb (the reference's show_pb.py): dump a serialized
    contract proto as text."""
    import os
    os.environ.setdefault("XLA_FLAGS", "")
    from paddle_tpu.compat import parse_config
    from paddle_tpu.utils import show_pb
    import pathlib
    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=8, learning_rate=0.1)\n"
        "x = data_layer(name='x', size=4)\n"
        "y = data_layer(name='y', size=2)\n"
        "out = fc_layer(input=x, size=2, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=out, label=y))\n")
    parsed = parse_config(str(cfg))
    blob = tmp_path / "model.bin"
    blob.write_bytes(parsed.model_proto().SerializeToString())
    txt = show_pb.show(str(blob))
    assert "ModelConfig" in txt and "__fc_layer_0__" in txt
    import pytest
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.bin"
        bad.write_bytes(b"\xff\xfe\xfd not a proto")
        show_pb.show(str(bad))
