"""--prev_batch_state: truncated-BPTT state carry across batches.

The reference carries RNN state over batch boundaries when
``--prev_batch_state`` is set (``Trainer.cpp:396-418``, ``Flags.cpp:73``)
so contiguous text trains as one long stream. Continuity property: feeding
a long sequence as two carried half-batches must produce the same forward
outputs as feeding it whole.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.core.network import Network
from paddle_tpu.optim import Adam
from paddle_tpu.trainer import events as ev
from paddle_tpu.trainer.trainer import SGD


@pytest.mark.parametrize("ltype,din", [("lstmemory", 12),
                                       ("gated_recurrent", 9),
                                       ("recurrent", 3)])
def test_carried_state_equals_unsplit_forward(ltype, din):
    from paddle_tpu.config.model_config import Input, LayerDef
    dsl.reset()
    dsl.data(name="x", size=din, is_sequence=True)
    dsl.current_graph().add(LayerDef(
        name="rnn", type=ltype, inputs=[Input("x")], bias=True))
    net = Network(dsl.current_graph(), outputs=["rnn"])
    params = net.init_params(jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    B, T = 2, 8
    v = rng.randn(B, T, din).astype(np.float32)
    full_mask = np.ones((B, T), np.float32)
    whole = net.apply(params, {"x": Argument(
        value=jnp.asarray(v), mask=jnp.asarray(full_mask))})["rnn"]

    half_mask = np.ones((B, T // 2), np.float32)
    first = net.apply(params, {"x": Argument(
        value=jnp.asarray(v[:, :T // 2]), mask=jnp.asarray(half_mask))})["rnn"]
    second = net.apply(
        params,
        {"x": Argument(value=jnp.asarray(v[:, T // 2:]),
                       mask=jnp.asarray(half_mask))},
        carried={"rnn": first.state})["rnn"]

    got = np.concatenate([np.asarray(first.value), np.asarray(second.value)],
                         axis=1)
    np.testing.assert_allclose(got, np.asarray(whole.value),
                               rtol=1e-5, atol=1e-5)


def test_reversed_layer_ignores_carry():
    from paddle_tpu.config.model_config import Input, LayerDef
    dsl.reset()
    dsl.data(name="x", size=9, is_sequence=True)
    dsl.current_graph().add(LayerDef(
        name="rnn", type="gated_recurrent", inputs=[Input("x")], bias=True,
        attrs={"reversed": True}))
    net = Network(dsl.current_graph(), outputs=["rnn"])
    params = net.init_params(jax.random.PRNGKey(0))
    v = np.random.RandomState(0).randn(2, 4, 9).astype(np.float32)
    feed = {"x": Argument(value=jnp.asarray(v),
                          mask=jnp.ones((2, 4), jnp.float32))}
    base = net.apply(params, feed)["rnn"]
    poisoned = net.apply(params, feed,
                         carried={"rnn": jnp.full((2, 3), 99.0)})["rnn"]
    np.testing.assert_allclose(np.asarray(base.value),
                               np.asarray(poisoned.value))


def _stream_reader(rng, batches=6, B=4, T=6, din=12, classes=2):
    def reader():
        for _ in range(batches):
            v = rng.randn(B, T, din).astype(np.float32)
            y = rng.randint(0, classes, size=B).astype(np.int32)
            m = np.ones((B, T), np.float32)
            yield {"x": Argument(value=jnp.asarray(v), mask=jnp.asarray(m)),
                   "label": Argument(value=jnp.asarray(y))}
    return reader


def test_trainer_threads_state_and_trains():
    """IMDB-style LSTM classifier with carried state trains; the carried
    dict is threaded across batches and reset at pass boundaries."""
    dsl.reset()
    x = dsl.data(name="x", size=12, is_sequence=True)
    lbl = dsl.data(name="label", size=2)
    h = dsl.lstmemory(input=x, name="lstm")
    pooled = dsl.last_seq(h)
    out = dsl.fc(input=pooled, size=2, act="softmax")
    cost = dsl.classification_cost(input=out, label=lbl)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=3e-3),
             prev_batch_state=True)
    assert tr._carry_layers == ["lstm"]
    rng = np.random.RandomState(0)
    costs = []
    tr.train(_stream_reader(rng), num_passes=3,
             event_handler=lambda e: costs.append(e.cost)
             if isinstance(e, ev.EndIteration) else None)
    assert tr._carried is not None and "lstm" in tr._carried
    hT, cT = tr._carried["lstm"]
    assert np.asarray(hT).shape == (4, 3)
    assert np.isfinite(costs[-1])


def test_recurrent_group_carry_continuity():
    """recurrent_group memories carry too: two carried half-batches equal
    the whole forward, like the flat-layer case."""
    dsl.reset()
    x = dsl.data(name="x", size=5, is_sequence=True)

    def step(xt):
        m = dsl.memory(name="h", size=5)
        return dsl.fc(input=[xt, m], size=5, act="tanh", name="h",
                      bias_attr=False)

    out = dsl.recurrent_group(step, x, name="grp")
    net = Network(dsl.current_graph(), outputs=[out.name])
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    B, T = 2, 8
    v = rng.randn(B, T, 5).astype(np.float32)
    m_full = jnp.ones((B, T), jnp.float32)
    whole = net.apply(params, {"x": Argument(value=jnp.asarray(v),
                                             mask=m_full)})[out.name]
    m_half = jnp.ones((B, T // 2), jnp.float32)
    first = net.apply(params, {"x": Argument(
        value=jnp.asarray(v[:, :T // 2]), mask=m_half)})[out.name]
    second = net.apply(
        params, {"x": Argument(value=jnp.asarray(v[:, T // 2:]),
                               mask=m_half)},
        carried={"grp": first.state["final"]})[out.name]
    got = np.concatenate([np.asarray(first.value),
                          np.asarray(second.value)], axis=1)
    np.testing.assert_allclose(got, np.asarray(whole.value),
                               rtol=1e-5, atol=1e-5)


def test_trainer_carries_group_state():
    """SGD(prev_batch_state=True) threads recurrent_group finals."""
    dsl.reset()
    x = dsl.data(name="x", size=5, is_sequence=True)
    lbl = dsl.data(name="label", size=2)

    def step(xt):
        m = dsl.memory(name="h", size=5)
        return dsl.fc(input=[xt, m], size=5, act="tanh", name="h",
                      bias_attr=False)

    grp = dsl.recurrent_group(step, x, name="grp")
    out = dsl.fc(input=dsl.last_seq(grp), size=2, act="softmax")
    cost = dsl.classification_cost(input=out, label=lbl)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=3e-3),
             prev_batch_state=True)
    assert tr._carry_layers == ["grp"]
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(3):
            v = rng.randn(4, 6, 5).astype(np.float32)
            y = rng.randint(0, 2, size=4).astype(np.int32)
            m = np.ones((4, 6), np.float32)
            yield {"x": Argument(value=jnp.asarray(v), mask=jnp.asarray(m)),
                   "label": Argument(value=jnp.asarray(y))}

    tr.train(reader, num_passes=1)
    assert tr._carried is not None
    assert set(tr._carried["grp"]) == {"grp@mem_h"}


def test_batch_size_change_resets_carry():
    """A smaller final batch must not crash the carried step — the carry
    resets on batch-size change (reference resetState semantics)."""
    dsl.reset()
    x = dsl.data(name="x", size=12, is_sequence=True)
    lbl = dsl.data(name="label", size=2)
    h = dsl.lstmemory(input=x, name="lstm")
    out = dsl.fc(input=dsl.last_seq(h), size=2, act="softmax")
    cost = dsl.classification_cost(input=out, label=lbl)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=3e-3),
             prev_batch_state=True)
    rng = np.random.RandomState(0)

    def reader():
        for B in (4, 4, 3):  # ragged final batch
            v = rng.randn(B, 6, 12).astype(np.float32)
            y = rng.randint(0, 2, size=B).astype(np.int32)
            m = np.ones((B, 6), np.float32)
            yield {"x": Argument(value=jnp.asarray(v), mask=jnp.asarray(m)),
                   "label": Argument(value=jnp.asarray(y))}

    tr.train(reader, num_passes=1)  # must not raise


def test_prev_batch_state_off_keeps_zero_boot():
    """Without the flag, every batch starts from zero state (no carry key
    in metrics, no retrace)."""
    dsl.reset()
    x = dsl.data(name="x", size=12, is_sequence=True)
    lbl = dsl.data(name="label", size=2)
    h = dsl.lstmemory(input=x, name="lstm")
    out = dsl.fc(input=dsl.last_seq(h), size=2, act="softmax")
    cost = dsl.classification_cost(input=out, label=lbl)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=3e-3))
    assert tr._carry_layers == []
    rng = np.random.RandomState(0)
    tr.train(_stream_reader(rng, batches=2), num_passes=1)
    assert tr._carried is None
