"""Reference-trained model + golden generation parity.

The reference's ``test_recurrent_machine_generation.cpp`` loads a
TRAINED model from the checked-in binary parameter files
(``rnn_gen_test_model_dir/t1``, ``Parameter::save`` format), runs
``sample_trainer_rnn_gen.conf`` / ``sample_trainer_nest_rnn_gen.conf``
in generating mode, and diffs the dumped text against golden files
(``r1.test.nobeam/.beam/.nest``). This test replicates it end-to-end:
the reference's OWN binary artifacts load here (compat/param_format.py),
the unmodified configs generate, and the formatted output equals the
reference's golden files byte-for-byte."""

import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.compat import parse_config
from paddle_tpu.compat.param_format import (load_v1_model_dir,
                                            load_v1_param, save_v1_param)
from paddle_tpu.core.argument import Argument
from paddle_tpu.core.generation import SequenceGenerator
from paddle_tpu.core.registry import get_layer_impl

TESTS = pathlib.Path("/root/reference/paddle/trainer/tests")
MODEL = TESTS / "rnn_gen_test_model_dir"
needs_ref = pytest.mark.skipif(not TESTS.exists(), reason="needs reference")


def test_param_format_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    arr = rng.randn(7, 3).astype(np.float32)
    save_v1_param(str(tmp_path / "w"), arr)
    back = load_v1_param(str(tmp_path / "w"))
    np.testing.assert_array_equal(back, arr.reshape(-1))
    raw = (tmp_path / "w").read_bytes()
    assert len(raw) == 16 + 21 * 4  # reference Header + payload


@needs_ref
def test_reference_binary_params_load():
    """The checked-in reference-trained files parse: 16-byte header
    (version 0, float32) + values (Parameter.cpp:247-251)."""
    params = load_v1_model_dir(str(MODEL / "t1"))
    assert set(params) == {"transtable", "wordvec"}
    np.testing.assert_array_equal(params["wordvec"].reshape(5, 5),
                                  np.eye(5, dtype=np.float32))
    tt = params["transtable"].reshape(5, 5)
    assert tt[0, 1] == 0.0 and tt[0, 0] == pytest.approx(-0.2)


def _load_gen(config_args: str, conf: str):
    parsed = parse_config(str(TESTS / conf), config_args)
    graph = parsed.model
    gen_name = [n for n, ld in graph.layers.items()
                if ld.type == "beam_search_group"][0]
    specs = get_layer_impl("beam_search_group").params(
        graph.layers[gen_name], [])
    raw = load_v1_model_dir(str(MODEL / "t1"))
    params = {}
    for spec in specs.values():
        params[spec.absolute_name] = jnp.asarray(
            raw[spec.absolute_name].reshape(spec.shape))
    return graph, gen_name, params


def _format_flat(tokens, scores, lengths, num_results):
    """The seqtext result_file format (``SequenceTextPrinter``,
    ``Evaluator.cpp:1375+``): one `id\\t toks` line per sample for a
    single result; `id NL rank\\tscore\\t toks ... NL` blocks for
    beams."""
    t, s, L = (np.asarray(tokens), np.asarray(scores),
               np.asarray(lengths))
    lines = []
    for b in range(t.shape[0]):
        if num_results == 1:
            toks = t[b, 0, : L[b, 0]]
            lines.append(f"{b}\t " + " ".join(str(int(x)) for x in toks))
        else:
            lines.append(f"{b}")
            for k in range(num_results):
                toks = t[b, k, : L[b, k]]
                lines.append(f"{k}\t{s[b, k]:g}\t "
                             + " ".join(str(int(x)) for x in toks))
            lines.append("")
    out = "\n".join(lines) + "\n"
    if num_results == 1:
        out += "\n"   # the reference dump ends single-result files with
        #               a blank line (SequenceTextPrinter final endl)
    return out


@needs_ref
def test_golden_generation_nobeam():
    """Greedy generation with the reference-trained params equals
    r1.test.nobeam byte-for-byte."""
    graph, gen_name, params = _load_gen("beam_search=0",
                                        "sample_trainer_rnn_gen.conf")
    rng = np.random.RandomState(0)
    outer = {"dummy_data_input": Argument(
        value=jnp.asarray(rng.rand(15, 2).astype(np.float32)))}
    sg = SequenceGenerator(graph, gen_name)
    tokens, scores, lengths = sg.generate(params, outer)
    got = _format_flat(tokens, scores, lengths, num_results=1)
    want = (MODEL / "r1.test.nobeam").read_text()
    assert got == want


@needs_ref
def test_golden_generation_beam():
    """Beam-2 generation (2 results/sample) equals r1.test.beam —
    including the reference's path scores (0 and -0.2, the summed log
    of the exp-activated step outputs)."""
    graph, gen_name, params = _load_gen("beam_search=1",
                                        "sample_trainer_rnn_gen.conf")
    rng = np.random.RandomState(0)
    outer = {"dummy_data_input": Argument(
        value=jnp.asarray(rng.rand(15, 2).astype(np.float32)))}
    sg = SequenceGenerator(graph, gen_name)
    tokens, scores, lengths = sg.generate(params, outer)
    got = _format_flat(tokens, scores, lengths, num_results=2)
    want = (MODEL / "r1.test.beam").read_text()
    assert got == want


@needs_ref
def test_golden_generation_nested():
    """sample_trainer_nest_rnn_gen.conf: an outer group concatenates the
    inner generation's per-subsequence results (the inner memory is
    read-only, so outer step i = inner generation on sub-batch i — the
    C++ comment in test_recurrent_machine_generation.cpp:135-138 states
    exactly this reduction). Output equals r1.test.nest: one outer
    sequence of 15 sub-results, sample id printed on the first only."""
    parsed = parse_config(str(TESTS / "sample_trainer_nest_rnn_gen.conf"),
                          "beam_search=0")
    graph = parsed.model
    # the inner beam group lives inside the outer group's sub-model
    outer_name = [n for n, ld in graph.layers.items()
                  if ld.type == "recurrent_layer_group"][0]
    sub = graph.layers[outer_name].attrs["sub_model"]
    gen_name = [n for n, ld in sub.layers.items()
                if ld.type == "beam_search_group"][0]
    specs = get_layer_impl("beam_search_group").params(
        sub.layers[gen_name], [])
    raw = load_v1_model_dir(str(MODEL / "t1"))
    params = {spec.absolute_name: jnp.asarray(
        raw[spec.absolute_name].reshape(spec.shape))
        for spec in specs.values()}

    rng = np.random.RandomState(0)
    # one outer sequence of 15 single-step subsequences (prepareInArgs
    # hasSubseq=True): each subsequence drives one inner generation
    outer_feed = {}
    for inp, meta in zip(sub.layers[gen_name].inputs,
                         sub.layers[gen_name].attrs["ins"]):
        outer_feed[inp.layer_name] = Argument(value=jnp.asarray(
            rng.rand(15, 2).astype(np.float32)))
    sg = SequenceGenerator(sub, gen_name)
    tokens, scores, lengths = sg.generate(params, {
        name: a for name, a in outer_feed.items()})
    t, L = np.asarray(tokens), np.asarray(lengths)
    lines = []
    for b in range(15):
        toks = " ".join(str(int(x)) for x in t[b, 0, : L[b, 0]])
        lines.append((f"{0}\t " if b == 0 else "\t ") + toks)
    got = "\n".join(lines) + "\n\n"
    want = (MODEL / "r1.test.nest").read_text()
    assert got == want


def test_cli_init_model_path_accepts_v1_dir(tmp_path):
    """`--init_model_path <dir>` loads a reference-format model directory
    (one Parameter::save file per parameter) into the trainer — the
    reference's resume/deploy contract (Trainer.cpp:229-250)."""
    import numpy as np

    from paddle_tpu.compat.param_format import save_v1_model_dir
    from paddle_tpu.config import dsl
    from paddle_tpu.optim import Momentum
    from paddle_tpu.trainer.cli import _init_params
    from paddle_tpu.trainer.trainer import SGD

    dsl.reset()
    x = dsl.data(name="x", size=4)
    lbl = dsl.data(name="label", size=3)
    out = dsl.fc(input=x, size=3, act="softmax", name="probs")
    cost = dsl.classification_cost(input=out, label=lbl)
    trainer = SGD(cost=cost,
                  update_equation=Momentum(learning_rate=0.1, momentum=0.9))

    rng = np.random.RandomState(3)
    golden = {name: rng.randn(*spec.shape).astype(np.float32)
              for name, spec in trainer.meta.items()}
    save_v1_model_dir(str(tmp_path / "pass-00001"), golden)

    _init_params(trainer, str(tmp_path / "pass-00001"))
    for name, want in golden.items():
        np.testing.assert_array_equal(
            np.asarray(trainer.params[name]), want)
