"""Bitwise neutrality of the in-step telemetry: stats-on training IS
stats-off training, across every step-composition feature.

The tentpole claim of the training-health plane (ISSUE 14): folding
the per-layer stat reduction + divergence sentry INTO the compiled
train step must not change a single trained bit — the stat reductions
read ``optimization_barrier``-fenced views so XLA cannot refuse the
update path's original fusion/rounding. Closure-enforced matrix (the
``test_exact_resume_matrix`` pattern): every step-composition feature
— {zero1, pipeline, grad_accum, async_input} — appears in at least one
cell, at least one cell composes several, and every cell asserts
zero hot-path recompiles through the hardened guards (each pinned
program variant compiles exactly once).

``period=2`` on purpose: the run alternates the stats-on and
stats-off program variants mid-stream (warm on batch 0, stats on every
2nd batch), so the equality also proves the VARIANT SWITCH itself is
trajectory-neutral — the production shape of
``--show_parameter_stats_period``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.optim import Adam
from paddle_tpu.parallel import create_mesh
from paddle_tpu.trainer import SGD

WIDTH, CLASSES, B = 8, 3, 16
BATCHES, PASSES = 4, 2

# cell -> {features}; the closure vocabulary matches the resume matrix
MATRIX = {
    "baseline": set(),
    "zero1": {"zero1"},
    "grad_accum": {"grad_accum"},
    "async_input": {"async_input"},
    "pipeline": {"pipeline"},
    "zero1_grad_accum_async": {"zero1", "grad_accum", "async_input"},
}
REQUIRED_FEATURES = {"zero1", "pipeline", "grad_accum", "async_input"}

HEALTH = {"period": 2, "sentry": True, "policy": "skip_batch"}


def test_matrix_closure():
    seen = set().union(*MATRIX.values())
    missing = REQUIRED_FEATURES - seen
    assert not missing, f"health matrix lost coverage for {missing}"
    assert any(len(f) >= 2 for f in MATRIX.values()), \
        "need at least one composed cell"


def _build(features, seed=5):
    dsl.reset()
    x = dsl.data(name="x", size=WIDTH)
    lbl = dsl.data(name="label", size=CLASSES)
    if "pipeline" in features:
        h = dsl.fc(input=x, size=WIDTH, act="tanh", name="blk0_0",
                   layer_attr={"device": 0})
        h = dsl.fc(input=h, size=WIDTH, act="tanh", name="blk1_0",
                   layer_attr={"device": 1})
        mesh = create_mesh(n_data=2, n_pipe=2)
    else:
        h = dsl.fc(input=x, size=WIDTH, act="tanh")
        h = dsl.dropout(input=h, rate=0.25)
        mesh = create_mesh(n_data=2) if "zero1" in features else None
    out = dsl.fc(input=h, size=CLASSES, act="softmax", name="out")
    cost = dsl.classification_cost(input=out, label=lbl)
    return SGD(cost=cost, update_equation=Adam(learning_rate=3e-3),
               mesh=mesh, seed=seed)


def _reader():
    rng = np.random.RandomState(11)
    X = rng.randn(BATCHES * B, WIDTH).astype(np.float32)
    W = rng.randn(WIDTH, CLASSES)
    Y = np.argmax(X @ W, axis=1).astype(np.int32)

    def reader():
        for i in range(0, BATCHES * B, B):
            yield {"x": Argument(value=jnp.asarray(X[i:i + B])),
                   "label": Argument(value=jnp.asarray(Y[i:i + B]))}

    return reader


def _train_kwargs(features):
    kw = {}
    if "zero1" in features:
        kw["zero1"] = True
    if "grad_accum" in features:
        kw["grad_accum_steps"] = 2
    if "async_input" in features:
        kw["async_load_data"] = True
    if "pipeline" in features:
        kw["pipeline"] = True
    return kw


def _final_state(tr):
    from paddle_tpu.trainer.checkpoint import _flatten
    params = {k: np.asarray(jax.device_get(v))
              for k, v in tr._params_for_save().items()}
    opt = _flatten(tr._opt_state_for_save())
    return params, opt, np.asarray(jax.device_get(tr._rng))


@pytest.mark.parametrize("cell", sorted(MATRIX), ids=sorted(MATRIX))
def test_stats_on_is_bitwise_stats_off(cell):
    features = MATRIX[cell]
    kw = _train_kwargs(features)
    reader = _reader()

    # both sides train as two one-pass calls so the armed side can
    # HARDEN its guards between warm and steady state (below)
    off = _build(features)
    for _ in range(PASSES):
        off.train(reader, num_passes=1, **kw)
    want_params, want_opt, want_rng = _final_state(off)
    assert off._train_step_stats is None  # really the stats-off path

    on = _build(features)
    on.train(reader, num_passes=1, health=HEALTH, **kw)
    # zero hot-path recompiles, the hardened form: freeze both pinned
    # variants' cache sizes after the warm pass — ANY later growth
    # raises RecompileError inside the loop's check()
    on.recompile_guard.harden()
    on.stats_recompile_guard.harden()
    on.train(reader, num_passes=1, **kw)  # health sticky (None keeps)
    got_params, got_opt, got_rng = _final_state(on)

    assert set(got_params) == set(want_params)
    for k in want_params:
        np.testing.assert_array_equal(got_params[k], want_params[k],
                                      err_msg=f"param {k} ({cell})")
    assert set(got_opt) == set(want_opt)
    for k in want_opt:
        np.testing.assert_array_equal(got_opt[k], want_opt[k],
                                      err_msg=f"opt {k} ({cell})")
    np.testing.assert_array_equal(got_rng, want_rng)

    # the telemetry really ran (snapshot present, nothing tripped) ...
    snap = on._health.snapshot()
    assert snap["steps"] == BATCHES * PASSES
    assert snap["sentry_trips"] == 0
    assert on._health.param_stats is not None
    # ... and the telemetry added exactly ONE program beyond the
    # stats-off run's own variant count (the pipeline step legitimately
    # traces twice while input shardings settle — on both sides)
    off_n = off.recompile_guard.count
    on_n = ((on.recompile_guard.count or 0)
            + (on.stats_recompile_guard.count or 0))
    if off_n is not None:
        assert on_n <= off_n + 1, (
            f"telemetry grew the program set {off_n} -> {on_n} ({cell})")
