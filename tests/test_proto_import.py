"""Wire-format import: expanded ModelConfig protos execute, *through* the
agent layers.

The reference engine consumes the expanded wire format directly —
recurrent groups arrive as sub-models with ``scatter_agent`` /
``gather_agent`` boundaries (``AgentLayer.cpp:209-210``) wired at runtime
by ``RecurrentGradientMachine``. These tests hold the TPU engine to the
same contract: a reference-style expanded proto (produced by the
golden-parity exporter) is imported by ``model_from_proto`` and executes
with the agent layers as the sub-model boundary slots, matching the
native DSL execution bit-for-bit.
"""

import re
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.layers  # noqa: F401
from paddle_tpu.compat.proto_export import model_to_proto
from paddle_tpu.compat.proto_import import model_from_proto
from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.core.network import Network
from paddle_tpu.core.registry import _LAYER_REGISTRY, get_layer_impl

REF_LAYERS = pathlib.Path("/root/reference/paddle/gserver/layers")


@pytest.mark.skipif(not REF_LAYERS.exists(), reason="needs reference")
def test_all_reference_register_layer_strings_resolve():
    """Every REGISTER_LAYER type string in the reference constructs an
    executable impl here (the VERDICT r3 gap: data_norm, out_prod,
    subseq, gather_agent, scatter_agent were missing)."""
    names = set()
    for f in REF_LAYERS.glob("*.cpp"):
        text = f.read_text(errors="ignore")
        names |= set(re.findall(r"REGISTER_LAYER\((\w+),", text))
        names |= set(re.findall(r"REGISTER_LAYER_CREATE_FUNC\((\w+),", text))
    missing = sorted(n for n in names if n not in _LAYER_REGISTRY)
    assert not missing, f"reference layer types not executable: {missing}"
    assert len(names) >= 80


def _rnn_model():
    """A net whose wire form carries the full agent plumbing: scatter
    agents (in_link), a memory agent (+delay1), and a gather agent."""
    dsl.reset()
    words = dsl.data(name="w", size=16, is_sequence=True)

    def step(x):
        mem = dsl.memory(name="rnn_out", size=8)
        return dsl.fc(input=[x, mem], size=8, act="tanh", name="rnn_out")

    g = dsl.recurrent_group(step, words, name="grp")
    pooled = dsl.pooling(input=g, pooling_type="max") \
        if hasattr(dsl, "pooling") else g
    return dsl.current_graph(), g.name


def test_expanded_group_roundtrip_executes():
    """DSL graph -> expanded wire proto (with agents) -> import -> run;
    outputs must equal the native execution exactly (same params, same
    scan program)."""
    model, out_name = _rnn_model()
    proto = model_to_proto(model)
    # the wire format really goes through the agent layers
    types = {l.name: l.type for l in proto.layers}
    assert "w@grp" in types and types["w@grp"] == "scatter_agent"
    assert types["rnn_out"] == "gather_agent"
    assert types["rnn_out+delay1@grp"] == "agent"

    imported = model_from_proto(proto.SerializeToString())
    # the imported sub-model keeps the agent layers as its boundary slots
    grp = imported.layers["rnn_out"]
    assert grp.type == "recurrent_layer_group"
    sub = grp.attrs["sub_model"]
    assert sub.layers["w@grp"].type == "scatter_agent"
    assert sub.layers["rnn_out+delay1@grp"].type == "agent"

    rng = np.random.RandomState(0)
    B, T = 3, 5
    mask = np.ones((B, T), np.float32)
    mask[1, 3:] = 0.0
    feed = {"w": Argument(
        value=jnp.asarray(rng.randn(B, T, 16).astype(np.float32)),
        mask=jnp.asarray(mask))}

    native = Network(model, outputs=[out_name])
    params = native.init_params(jax.random.PRNGKey(0))
    want = np.asarray(native.apply(params, feed)[out_name].value)

    net = Network(imported, outputs=["rnn_out"])
    # imported params carry the wire-scoped names (`_rnn_out@grp.w0`);
    # the native DSL keeps sub-layer names unscoped — same tensors either
    # way, so translate and the executions must agree exactly
    assert set(net.param_specs) == {
        n.replace("_rnn_out.", "_rnn_out@grp.") for n in native.param_specs}
    imported_params = {
        n.replace("_rnn_out.", "_rnn_out@grp."): v
        for n, v in params.items()}
    got = np.asarray(net.apply(imported_params, feed)["rnn_out"].value)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_imported_group_trains():
    """Gradients flow through the imported agent-layer graph (the memory
    agent feed slot sits on the differentiation path)."""
    model, out_name = _rnn_model()
    imported = model_from_proto(model_to_proto(model).SerializeToString())
    net = Network(imported, outputs=["rnn_out"])
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    feed = {"w": Argument(
        value=jnp.asarray(rng.randn(2, 4, 16).astype(np.float32)),
        mask=jnp.ones((2, 4), jnp.float32))}

    def loss(p):
        return jnp.sum(net.apply(p, feed)["rnn_out"].value ** 2)

    g = jax.grad(loss)(params)
    for name in ("_rnn_out@grp.w0", "_rnn_out@grp.w1", "_rnn_out@grp.wbias"):
        assert float(jnp.max(jnp.abs(g[name]))) > 0.0, name


def test_direct_agent_impls():
    """get_layer_impl resolves the agent types (VERDICT: KeyError before)
    and the impls carry the feed-slot protocol for input-less use."""
    for t in ("gather_agent", "scatter_agent", "agent"):
        impl = get_layer_impl(t)
        assert getattr(impl, "feed_slot", t == "gather_agent") or \
            t == "gather_agent"
    assert get_layer_impl("out_prod") is not None
    assert get_layer_impl("data_norm") is not None
    assert get_layer_impl("subseq") is not None


def test_out_prod_layer_helper_now_executes():
    """The compat helper out_prod_layer (which previously emitted a type
    the engine rejected) builds a runnable graph."""
    from paddle_tpu.compat.config_parser import begin_parse
    from paddle_tpu.compat.trainer_config_helpers import layers as cl
    dsl.reset()
    begin_parse()
    a = dsl.data(name="a", size=3)
    b = dsl.data(name="b", size=4)
    out = cl.out_prod_layer(input1=a, input2=b)
    net = Network(dsl.current_graph(), outputs=[out.name])
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    fa = rng.randn(2, 3).astype(np.float32)
    fb = rng.randn(2, 4).astype(np.float32)
    got = np.asarray(net.apply(params, {
        "a": Argument(value=jnp.asarray(fa)),
        "b": Argument(value=jnp.asarray(fb))})[out.name].value)
    want = np.einsum("bi,bj->bij", fa, fb).reshape(2, 12)
    np.testing.assert_allclose(got, want, rtol=1e-6)
