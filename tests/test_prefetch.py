"""Async input pipeline (`data/prefetch.py`): ordering, bounded depth /
backpressure, worker-exception propagation, clean shutdown; bucketing
exactness (padded rows contribute ZERO loss and grad via the row mask);
and the recompile-guard — a ragged corpus compiles at most bucket-count
step variants, counted by the jit-cache probe."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.config import dsl
from paddle_tpu.data import (DataFeeder, LengthBuckets, PrefetchPipeline,
                             ROW_MASK_KEY, dense_vector, integer_value,
                             integer_value_sequence, prefetch_reader)
from paddle_tpu.data.prefetch import RecompileGuard, jit_cache_size
from paddle_tpu.optim import Momentum
from paddle_tpu.trainer import SGD
from paddle_tpu.utils.stat import StatRegistry


# ------------------------------------------------------------- pipeline
def test_prefetch_preserves_order():
    pipe = PrefetchPipeline(lambda: iter(range(20)), place=False)
    assert list(pipe) == list(range(20))


def test_prefetch_bounded_depth_backpressure():
    produced = []

    def reader():
        for i in range(100):
            produced.append(i)
            yield i

    pipe = PrefetchPipeline(reader, depth=2, place=False)
    deadline = time.time() + 5.0
    # the worker runs ahead only up to the queue bound (+1 in-prepare)
    while len(produced) < 3 and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.3)  # would overrun here if the queue were unbounded
    assert len(produced) <= 2 + 1, produced
    assert pipe.get() == 0  # consuming frees a slot
    deadline = time.time() + 5.0
    while len(produced) < 4 and time.time() < deadline:
        time.sleep(0.01)
    assert 4 <= len(produced) <= 4 + 1
    pipe.close()


def test_prefetch_propagates_worker_exception_after_good_items():
    def reader():
        yield 1
        yield 2
        raise ValueError("decode exploded")

    pipe = PrefetchPipeline(reader, place=False)
    assert pipe.get() == 1
    assert pipe.get() == 2
    with pytest.raises(ValueError, match="decode exploded"):
        pipe.get()
    # after the failure the stream is closed, not wedged
    with pytest.raises(StopIteration):
        pipe.get()


def test_prefetch_feeder_exception_propagates():
    def bad_feeder(b):
        raise KeyError("bad batch")

    pipe = PrefetchPipeline(lambda: iter([[1]]), feeder=bad_feeder,
                            place=False)
    with pytest.raises(KeyError):
        pipe.get()


def test_prefetch_close_is_clean_and_idempotent():
    release = threading.Event()

    def reader():
        for i in range(1000):
            yield i
            release.wait(0.001)

    pipe = PrefetchPipeline(reader, depth=2, place=False)
    assert pipe.get() == 0
    pipe.close()
    pipe.close()  # idempotent
    assert not pipe._thread.is_alive()
    with pytest.raises(StopIteration):
        pipe.get()


def test_prefetch_records_wait_and_decode_stats():
    reg = StatRegistry("t")
    pipe = PrefetchPipeline(lambda: iter([[1], [2]]),
                            feeder=lambda b: b, place=False, registry=reg)
    assert list(pipe) == [[1], [2]]
    assert reg.get("prefetch/decode").count == 2
    assert reg.get("prefetch/wait").count >= 2
    assert pipe.data_wait >= 0.0


def test_prefetch_reader_wrapper_marks_and_streams():
    r = prefetch_reader(lambda: iter([1, 2, 3]), place=False)
    assert r.is_prefetched
    assert list(r()) == [1, 2, 3]
    # a second call re-streams (fresh pipeline per pass)
    assert list(r()) == [1, 2, 3]


def test_prefetched_reader_trains_and_rejects_stray_feeder():
    rng = np.random.RandomState(6)
    data = [(rng.randn(4).astype(np.float32), int(rng.randint(3)))
            for _ in range(8)]
    feeder = DataFeeder({"x": dense_vector(4), "y": integer_value(3)})
    reader = prefetch_reader(lambda: iter([data[:4], data[4:]]),
                             feeder=feeder)
    t = _fc_trainer()
    # passing ANOTHER feeder alongside a prefetched reader is a
    # misconfiguration the trainer must reject loudly, not ignore
    with pytest.raises(ValueError, match="prefetched"):
        t.train(reader, feeder=feeder, num_passes=1)
    t.train(reader, num_passes=2)  # the wrapped form trains
    assert t.step_breakdown()["steps"] == 4
    assert not any(th.name == "prefetch-worker" and th.is_alive()
                   for th in threading.enumerate())


# ------------------------------------------------------------- buckets
def test_length_buckets_pad_len():
    b = LengthBuckets([16, 32, 64])
    assert b.pad_len(1) == 16
    assert b.pad_len(16) == 16
    assert b.pad_len(17) == 32
    assert b.pad_len(64) == 64
    # beyond the last edge: multiples of it, still a bounded menu
    assert b.pad_len(65) == 128
    assert b.pad_len(129) == 192
    with pytest.raises(ValueError):
        LengthBuckets([])
    with pytest.raises(ValueError):
        LengthBuckets([4, 4])


def test_feeder_length_buckets_shape_menu():
    feeder = DataFeeder({"w": integer_value_sequence(50)},
                        length_buckets=[8, 16])
    feed = feeder([([1, 2, 3],), ([4] * 10,)])
    assert feed["w"].value.shape == (2, 16)
    feed = feeder([([1, 2],)])
    assert feed["w"].value.shape == (1, 8)
    # masks mark exactly the real tokens
    assert float(jnp.sum(feed["w"].mask)) == 2.0


def test_feeder_batch_buckets_pads_rows_with_row_mask():
    feeder = DataFeeder({"x": dense_vector(3), "y": integer_value(2)},
                        batch_buckets=[4])
    batch = [(np.ones(3, np.float32), 1), (np.zeros(3, np.float32), 0)]
    feed = feeder(batch)
    assert feed["x"].value.shape == (4, 3)
    assert feed["y"].value.shape == (4,)
    np.testing.assert_array_equal(np.asarray(feed[ROW_MASK_KEY].value),
                                  [1.0, 1.0, 0.0, 0.0])
    # a full batch keeps the SAME pytree structure (no recompile flip)
    full = feeder([(np.ones(3, np.float32), 1)] * 4)
    assert ROW_MASK_KEY in full
    np.testing.assert_array_equal(np.asarray(full[ROW_MASK_KEY].value),
                                  [1.0] * 4)


def _fc_trainer(seed=0):
    dsl.reset()
    x = dsl.data("x", size=4)
    y = dsl.data("y", size=3)
    h = dsl.fc(input=x, size=3, act="softmax")
    cost = dsl.classification_cost(input=h, label=y)
    return SGD(cost=cost, update_equation=Momentum(learning_rate=0.1),
               seed=seed)


def test_padded_rows_contribute_zero_loss_and_grad():
    """The acceptance shape: stepping on [5 real rows] and on [5 real +
    3 dead rows, row-masked] yields the SAME cost, classification error,
    and updated parameters — padding is exactly ignored, including the
    batch-mean denominator."""
    rng = np.random.RandomState(0)
    batch = [(rng.randn(4).astype(np.float32), int(rng.randint(3)))
             for _ in range(5)]
    plain = DataFeeder({"x": dense_vector(4), "y": integer_value(3)})
    padded = DataFeeder({"x": dense_vector(4), "y": integer_value(3)},
                        batch_buckets=[8])

    t1, t2 = _fc_trainer(), _fc_trainer()
    key = jax.random.PRNGKey(7)
    p1, _, m1 = t1._train_step(t1.params, t1.opt_state, plain(batch),
                               key, jnp.int32(0))
    p2, _, m2 = t2._train_step(t2.params, t2.opt_state, padded(batch),
                               key, jnp.int32(0))
    assert float(m1["cost"]) == pytest.approx(float(m2["cost"]), rel=1e-6)
    e1, c1 = (float(v) for v in m1["classification_error"])
    e2, c2 = (float(v) for v in m2["classification_error"])
    assert (e1, c1) == (e2, c2)
    assert c2 == 5.0  # dead rows not in the count
    for name in p1:
        np.testing.assert_allclose(np.asarray(p1[name]),
                                   np.asarray(p2[name]), rtol=1e-6,
                                   atol=1e-7)


def test_row_mask_stays_f32_under_bf16_compute():
    """Masks are f32 count data (CLAUDE.md): _cast_compute must exempt
    the ROW_MASK_KEY entry by key, not rely on callers re-reading the
    uncast feed — and a bf16 step on a padded batch must still train."""
    import jax.numpy as jnp
    dsl.reset()
    x = dsl.data("x", size=4)
    y = dsl.data("y", size=3)
    h = dsl.fc(input=x, size=3, act="softmax")
    cost = dsl.classification_cost(input=h, label=y)
    t = SGD(cost=cost, update_equation=Momentum(learning_rate=0.1),
            compute_dtype="bfloat16")
    feeder = DataFeeder({"x": dense_vector(4), "y": integer_value(3)},
                        batch_buckets=[8])
    feed = feeder([(np.ones(4, np.float32), 1)] * 5)
    cast = t._cast_compute(feed)
    assert cast[ROW_MASK_KEY].value.dtype == jnp.float32
    assert cast["x"].value.dtype == jnp.bfloat16
    _, _, m = t._train_step(t.params, t.opt_state, feed,
                            jax.random.PRNGKey(0), jnp.int32(0))
    assert np.isfinite(float(m["cost"]))
    assert float(m["classification_error"][1]) == 5.0


def test_batch_bucket_overflow_raises():
    """Batch sizes are a closed menu: a batch beyond the largest bucket
    is a reader/config mismatch, not something to silently pad around."""
    feeder = DataFeeder({"x": dense_vector(3)}, batch_buckets=[4])
    with pytest.raises(ValueError, match="largest batch bucket"):
        feeder([(np.ones(3, np.float32),)] * 5)


def test_padded_sequence_rows_have_dead_masks():
    """A dead row on a sequence input is an all-zero token mask — the
    existing mask-as-count semantics every layer already honors."""
    feeder = DataFeeder({"w": integer_value_sequence(20)},
                        length_buckets=[8], batch_buckets=[4])
    feed = feeder([([1, 2, 3],), ([4, 5],)])
    assert feed["w"].value.shape == (4, 8)
    mask = np.asarray(feed["w"].mask)
    assert mask[:2].sum() == 5.0
    assert mask[2:].sum() == 0.0  # padded rows: fully masked


# ------------------------------------------------------- recompile guard
def _seq_trainer(vocab=30, recompile_warn=8):
    dsl.reset()
    w = dsl.data("w", size=vocab)
    y = dsl.data("y", size=2)
    e = dsl.embedding(input=w, size=8, vocab_size=vocab)
    p = dsl.pooling(input=e, pooling_type="avg")
    h = dsl.fc(input=p, size=2, act="softmax")
    cost = dsl.classification_cost(input=h, label=y)
    return SGD(cost=cost, update_equation=Momentum(learning_rate=0.1),
               recompile_warn=recompile_warn)


def _ragged_reader(vocab=30, n_batches=8, bsz=2):
    rng = np.random.RandomState(3)
    lengths = rng.randint(1, 60, size=n_batches * bsz)

    def reader():
        it = iter(lengths)
        for _ in range(n_batches):
            yield [(list(rng.randint(0, vocab, size=next(it))),
                    int(rng.randint(2))) for _ in range(bsz)]
    return reader


def test_ragged_corpus_bucketing_bounds_recompiles():
    vocab = 30
    buckets = [16, 32, 64]
    feeder = DataFeeder({"w": integer_value_sequence(vocab),
                         "y": integer_value(2)}, length_buckets=buckets)
    t = _seq_trainer(vocab)
    t.train(_ragged_reader(vocab), feeder=feeder, num_passes=1)
    n = t.recompile_guard.count
    assert n is not None and n <= len(buckets), n
    assert not t.recompile_guard.warned


def test_unbucketed_ragged_corpus_thrashes_and_guard_warns(caplog):
    vocab = 30
    # pad_multiple=1: every distinct raw max-length is its own shape
    feeder = DataFeeder({"w": integer_value_sequence(vocab),
                         "y": integer_value(2)}, pad_multiple=1)
    t = _seq_trainer(vocab, recompile_warn=3)
    import logging
    plogger = logging.getLogger("paddle_tpu")
    plogger.addHandler(caplog.handler)
    try:
        t.train(_ragged_reader(vocab), feeder=feeder, num_passes=1)
    finally:
        plogger.removeHandler(caplog.handler)
    n = t.recompile_guard.count
    assert n is not None and n > 3, n
    assert t.recompile_guard.warned
    assert "compile cache" in caplog.text


def test_jit_cache_probe_counts_variants():
    f = jax.jit(lambda x: x * 2)
    assert jit_cache_size(f) in (0, None)
    f(jnp.ones((2,)))
    f(jnp.ones((3,)))
    assert jit_cache_size(f) == 2
    g = RecompileGuard(f, warn_after=1, name="probe")
    assert g.check() == 2
    assert g.warned
    # no-probe objects disable the guard instead of breaking training
    assert jit_cache_size(object()) is None


# ----------------------------------------------------- trainer integration
def test_async_training_matches_sync_training():
    """Same data, same seeds: the async pipeline must be a pure overlap
    optimization — costs identical batch for batch."""
    rng = np.random.RandomState(1)
    data = [(rng.randn(4).astype(np.float32), int(rng.randint(3)))
            for _ in range(12)]
    feeder = DataFeeder({"x": dense_vector(4), "y": integer_value(3)})

    def reader():
        for i in range(0, len(data), 4):
            yield data[i:i + 4]

    costs = {}
    for mode in ("sync", "async"):
        t = _fc_trainer(seed=5)
        got = []
        t.train(reader, feeder=feeder, num_passes=2,
                async_load_data=(mode == "async"),
                event_handler=lambda e: got.append(e.cost)
                if hasattr(e, "cost") else None)
        costs[mode] = got
    assert costs["sync"] == pytest.approx(costs["async"], rel=1e-6)
    assert len(costs["sync"]) == 6


def test_step_breakdown_accumulates_all_parts():
    rng = np.random.RandomState(2)
    data = [(rng.randn(4).astype(np.float32), int(rng.randint(3)))
            for _ in range(8)]
    feeder = DataFeeder({"x": dense_vector(4), "y": integer_value(3)})
    t = _fc_trainer()
    t.train(lambda: iter([data[:4], data[4:]]), feeder=feeder, num_passes=1)
    s = t.step_breakdown()
    assert s["steps"] == 2
    assert s["steps_per_sec"] > 0
    assert s["compute_frac"] > 0
    # denominator is TRUE wall time: the four parts cover most-but-not-
    # all of it (BeginIteration handlers / rng splits are outside), so
    # the sum must be close to 1 from BELOW, never above
    fracs = sum(s[f"{p}_frac"] for p in ("data_wait", "h2d", "compute",
                                         "callback"))
    assert 0.5 < fracs <= 1.0 + 1e-9


def test_async_pipeline_closed_when_loop_raises():
    """A raising event handler (the v2 early-stop idiom) must not leak
    the prefetch worker thread — train() closes the pipe in a finally."""
    rng = np.random.RandomState(0)
    data = [(rng.randn(4).astype(np.float32), int(rng.randint(3)))
            for _ in range(8)]
    feeder = DataFeeder({"x": dense_vector(4), "y": integer_value(3)})
    t = _fc_trainer()

    class Stop(Exception):
        pass

    def handler(e):
        if e.__class__.__name__ == "EndIteration":
            raise Stop

    with pytest.raises(Stop):
        t.train(lambda: iter([data[:4], data[4:]] * 50), feeder=feeder,
                num_passes=1, async_load_data=True, event_handler=handler)
    assert not any(th.name == "prefetch-worker" and th.is_alive()
                   for th in threading.enumerate())


def test_host_evaluators_never_see_padded_rows():
    """Config-declared (host-side) evaluators on NON-sequence layers get
    the live-row prefix only — batch-bucket padding is exactly ignored
    on this path too, not just in the cost."""
    def build(batch_buckets):
        dsl.reset()
        x = dsl.data("x", size=4)
        y = dsl.data("y", size=3)
        h = dsl.fc(input=x, size=3, act="softmax")
        cost = dsl.classification_cost(input=h, label=y)
        dsl.evaluator("classification_error", input=h, label=y,
                      name="host_err")
        t = SGD(cost=cost, update_equation=Momentum(learning_rate=0.1))
        f = DataFeeder({"x": dense_vector(4), "y": integer_value(3)},
                       batch_buckets=batch_buckets)
        return t, f

    rng = np.random.RandomState(4)
    batch = [(rng.randn(4).astype(np.float32), int(rng.randint(3)))
             for _ in range(5)]
    vals = {}
    for tag, buckets in (("plain", None), ("padded", [8])):
        t, f = build(buckets)
        t.train(lambda: iter([batch]), feeder=f, num_passes=1)
        vals[tag] = t.host_eval_values()["host_err"]
    assert vals["padded"] == pytest.approx(vals["plain"], rel=1e-6)
