"""End-to-end checkpoint/resume through the trainer.

The TPU analogue of the reference's restart story: pserver checkpoint +
``--start_pass`` resume (`go/pserver/service.go:272+`,
`Trainer.cpp:229-250`). A run that crashes mid-job and resumes from its
checkpoint must produce exactly the state of an uninterrupted run
(params AND optimizer slots, since momentum is part of the typed buffer
set, `parameter/Parameter.h:46`).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.config import dsl
from paddle_tpu.data import DataFeeder, dense_vector, integer_value
from paddle_tpu.dist.checkpoint import Checkpointer
from paddle_tpu.optim import Momentum
from paddle_tpu.trainer import SGD


def _build():
    dsl.reset()
    x = dsl.data(name="x", size=8)
    lab = dsl.data(name="label", size=4)
    out = dsl.fc(input=x, size=4, act="softmax")
    return dsl.classification_cost(input=out, label=lab)


def _reader():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    Y = np.argmax(X[:, :4], axis=1)

    def reader():
        for i in range(0, 64, 16):
            yield [(X[j], int(Y[j])) for j in range(i, i + 16)]

    return reader


def test_resume_matches_uninterrupted_run(tmp_path):
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    reader = _reader()

    def make_trainer():
        cost = _build()
        return SGD(cost=cost,
                   update_equation=Momentum(learning_rate=0.1, momentum=0.9),
                   seed=7)

    # uninterrupted: 4 passes straight
    t_full = make_trainer()
    t_full.train(reader, feeder=feeder, num_passes=4)

    # interrupted: 2 passes, checkpoint, "crash", resume to 4
    ck = Checkpointer(str(tmp_path), saving_period=1)
    t_a = make_trainer()
    t_a.train(reader, feeder=feeder, num_passes=2, checkpointer=ck)
    t_b = make_trainer()  # fresh process state
    t_b.train(reader, feeder=feeder, num_passes=4,
              checkpointer=Checkpointer(str(tmp_path), saving_period=1))

    for k in t_full.params:
        np.testing.assert_allclose(np.asarray(t_full.params[k]),
                                   np.asarray(t_b.params[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_restore_skips_when_no_checkpoint(tmp_path):
    cost = _build()
    tr = SGD(cost=cost, update_equation=Momentum(learning_rate=0.1),
             seed=1)
    ck = Checkpointer(str(tmp_path))
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    tr.train(_reader(), feeder=feeder, num_passes=1, checkpointer=ck)
    # a checkpoint now exists and restores cleanly
    restored = ck.restore()
    assert restored is not None
    params, opt_flat, meta = restored
    assert meta["pass_id"] == 0 and set(params) == set(tr.params)


def test_midpass_checkpoint_restarts_same_pass(tmp_path):
    """A batch-cadence (mid-pass) checkpoint resumes at the SAME pass so
    the untrained remainder of the interrupted pass is not skipped."""
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    reader = _reader()
    ck = Checkpointer(str(tmp_path), saving_period=10**9,
                      saving_period_by_batches=2)
    cost = _build()
    t_a = SGD(cost=cost, update_equation=Momentum(learning_rate=0.1), seed=3)
    t_a.train(reader, feeder=feeder, num_passes=1, checkpointer=ck)
    # last save was mid-pass (batch cadence); meta says batch_id>0
    _, _, meta = ck.restore()
    assert meta["batch_id"] > 0 and not meta["end_of_pass"]

    passes_run = []
    t_b = SGD(cost=_build(), update_equation=Momentum(learning_rate=0.1),
              seed=3)
    t_b.train(reader, feeder=feeder, num_passes=2,
              checkpointer=Checkpointer(str(tmp_path), saving_period=10**9),
              event_handler=lambda e: passes_run.append(e.pass_id)
              if hasattr(e, "pass_id") else None)
    # restarted pass 0 (not skipped to pass 1), then ran pass 1
    assert 0 in passes_run and 1 in passes_run
