"""Continuous batching on the serving generate path
(``serving/batcher.py`` + ``core/generation.py:DecodeSession``).

The engine model is length-controlled by construction: the decoder's
EOS logit is proportional to the (boot) memory sum, so a positive input
vector finishes within ~2 steps and a negative one never emits EOS and
runs to ``max_length`` — deterministic short/long traffic with fat
margins (no near-ties for cross-batch-width token flips to hide in).

What must hold:

- answers are identical to convoy (non-continuous) batching,
- short requests retire at chunk boundaries while a long neighbor is
  still decoding (the anti-convoy property), with queued requests
  admitted into freed lanes mid-decode,
- deadlines are enforced *mid-decode*, answering the expired lane
  without disturbing its neighbors,
- the closed-menu 400 for off-menu gen opts carries the warmed
  ``allowed`` menu end-to-end (engine, wire, typed client),
- the decode observability series (per-request decode_steps,
  lane occupancy) land in the snapshot and Prometheus export,
- zero post-warmup recompiles (the hardened guards would kill the
  worker; ``engine.fatal is None`` asserts it).
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.config import dsl
from paddle_tpu.core.network import Network
from paddle_tpu.core.registry import get_layer_impl
from paddle_tpu.data import dense_vector
from paddle_tpu.serving import (BadRequest, DeadlineExceeded,
                                ServingClient, ServingEngine,
                                ServingPredictor, make_server)

V, E, H = 6, 4, 5
EOS = 1
K = 3


def _length_controlled_graph(max_length, beam_size=K):
    dsl.reset()
    src = dsl.data("src", size=H)
    boot = dsl.fc(src, size=H, act="tanh", name="boot", bias_attr=False)

    def step(prev_emb):
        m = dsl.memory(name="h", size=H, boot_layer=boot)
        h = dsl.fc([prev_emb, m], size=H, act="tanh", name="h",
                   bias_attr=False)
        return dsl.fc(h, size=V, act="softmax", name="prob",
                      bias_attr=False)

    dsl.beam_search(
        step, [dsl.GeneratedInput(size=V, embedding_name="gen_emb",
                                  embedding_size=E)],
        bos_id=0, eos_id=EOS, beam_size=beam_size, max_length=max_length,
        name="gen")
    return dsl.current_graph()


def _length_controlled_params(graph):
    """EOS logit = 3 * sum(memory); memory = tanh(2*src) decayed by
    tanh each step. Positive src -> EOS dominates immediately (finish
    in <= 2 steps); negative src -> EOS is ~e^-14 (never finishes)."""
    net = Network(graph, outputs=["boot"])
    params = dict(net.init_params(jax.random.PRNGKey(0)))
    boot_key = next(k for k in params if "boot" in k)
    params[boot_key] = jnp.asarray(2.0 * np.eye(H, dtype=np.float32))
    for _, spec in get_layer_impl("beam_search_group").params(
            graph.layers["gen"], []).items():
        params[spec.absolute_name] = jnp.zeros(spec.shape, jnp.float32)
    params["_h.w1"] = jnp.asarray(np.eye(H, dtype=np.float32))
    u = np.zeros((H, V), np.float32)
    u[:, EOS] = 3.0
    params["_prob.w0"] = jnp.asarray(u)
    params["gen_emb"] = jnp.zeros((V, E), jnp.float32)
    return params


def _short():
    return ([1.0] * H,)


def _long():
    return ([-1.0] * H,)


def _build_engine(max_length=24, decode_chunk=2, continuous=True,
                  max_batch=4, **eng_kw):
    graph = _length_controlled_graph(max_length)
    params = _length_controlled_params(graph)
    pred = ServingPredictor(graph, params, ["gen"],
                            {"src": dense_vector(H)},
                            batch_buckets=[1, 2, 4][:max(
                                1, max_batch.bit_length())],
                            gen_decode_chunk=decode_chunk)
    return ServingEngine(pred, max_batch=max_batch, batch_timeout_ms=2.0,
                         continuous_batching=continuous, **eng_kw).start()


@pytest.fixture(scope="module")
def engines():
    cont = _build_engine(continuous=True)
    convoy = _build_engine(continuous=False)
    yield cont, convoy
    cont.shutdown()
    convoy.shutdown()


def _gather(eng, samples, deadline_ms=None):
    reqs = [eng.submit(s, kind="generate", deadline_ms=deadline_ms)
            for s in samples]
    for r in reqs:
        assert r.event.wait(120.0), "engine hung"
    return reqs


def test_continuous_answers_match_convoy(engines):
    cont, convoy = engines
    samples = [_short(), _long(), _short(), _long(), _short()]
    got_c = _gather(cont, samples)
    got_v = _gather(convoy, samples)
    for s, rc, rv in zip(samples, got_c, got_v):
        assert rc.error is None and rv.error is None
        ks = rc.result["sequences"]
        vs = rv.result["sequences"]
        assert [q["tokens"] for q in ks] == [q["tokens"] for q in vs], s
        for a, b in zip(ks, vs):
            assert abs(a["score"] - b["score"]) < 1e-5
    # the length control actually controls: shorts end at <= 2 tokens,
    # longs run the full max_length
    assert all(len(q["tokens"]) <= 2
               for q in got_c[0].result["sequences"])
    assert any(len(q["tokens"]) == 24
               for q in got_c[1].result["sequences"])
    assert cont.fatal is None and convoy.fatal is None


def test_short_requests_escape_the_convoy(engines):
    cont, _ = engines
    base = cont.metrics.counters["continuous_admissions_total"]
    long_req = cont.submit(_long(), kind="generate")
    shorts = [cont.submit(_short(), kind="generate") for _ in range(6)]
    for r in shorts:
        assert r.event.wait(120.0)
        assert r.error is None
    # every short answered while the long lane is still decoding: the
    # convoy is broken (a coalesced batch would answer them together)
    assert not long_req.event.is_set(), \
        "short requests waited for the slow lane (convoy not broken)"
    assert long_req.event.wait(120.0)
    assert long_req.error is None
    # 7 requests through 4 lanes: some were admitted mid-decode
    assert (cont.metrics.counters["continuous_admissions_total"]
            > base)
    snap = cont.metrics.snapshot()
    assert snap["lane_occupancy"]["count"] > 0
    assert snap["decode_chunks_total"] > 0
    # per-request decode accounting: shorts paid ~1 chunk, the long
    # lane paid max_length steps
    assert snap["decode_steps"]["count"] >= 7
    assert cont.metrics.counters["decode_steps_saved_total"] > 0
    assert cont.fatal is None


def test_deadline_enforced_mid_decode():
    """A lane whose deadline passes while the search is still running is
    answered ``DeadlineExceeded`` at the next chunk boundary — not when
    the batch finishes — and its neighbor completes untouched."""
    # 192 one-step chunks of a never-ending search, with a floor put
    # under each chunk's wall time: relying on the model being slow
    # enough broke when host drift made the warmed tiny search outrun
    # the 40 ms deadline entirely (the whole decode beat the deadline,
    # doomed was answered cleanly). 5 ms/chunk pins the full search at
    # >= ~1 s regardless of drift, so the deadline ALWAYS lands
    # strictly mid-decode: admission takes ~1 chunk, expiry by ~chunk 8
    # of 192 — same spirit as the chaos plane's straggler injection,
    # modeling a slower device step without touching semantics.
    eng = _build_engine(max_length=192, decode_chunk=1, max_batch=2)
    real_chunk = eng._session.run_chunk

    def slow_chunk(*a, **kw):
        out = real_chunk(*a, **kw)
        time.sleep(0.005)
        return out

    eng._session.run_chunk = slow_chunk
    try:
        neighbor = eng.submit(_long(), kind="generate")
        doomed = eng.submit(_long(), kind="generate", deadline_ms=40.0)
        assert doomed.event.wait(120.0)
        assert isinstance(doomed.error, DeadlineExceeded)
        assert "mid-decode" in str(doomed.error)
        assert not neighbor.event.is_set(), \
            "the deadline answer waited for the whole batch"
        assert neighbor.event.wait(120.0)
        assert neighbor.error is None
        assert any(len(q["tokens"]) == 192
                   for q in neighbor.result["sequences"])
        assert eng.fatal is None
    finally:
        eng.shutdown()


def test_convoy_mode_records_decode_steps(engines):
    _, convoy = engines
    _gather(convoy, [_short(), _short()])
    snap = convoy.metrics.snapshot()
    assert snap["decode_steps"]["count"] > 0
    # early exit: the chunked search paid less than max_length
    assert convoy.metrics.counters["decode_steps_saved_total"] > 0


def test_gen_opts_400_carries_allowed_menu(engines):
    cont, _ = engines
    with pytest.raises(BadRequest) as ei:
        cont.submit(_short(), kind="generate", beam_size=K + 2)
    assert ei.value.allowed == {"beam_size": [K], "max_length": [24]}
    # and over the wire, through the typed client
    server = make_server(cont, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        client = ServingClient(port=server.server_address[1])
        with pytest.raises(BadRequest) as ei:
            client.generate(_short(), max_length=999)
        assert ei.value.allowed == {"beam_size": [K], "max_length": [24]}
        got = client.generate(_short())
        assert len(got["sequences"]) == K
    finally:
        server.shutdown()


def test_bucket_dependent_static_shapes_stand_down():
    """A sequence-valued StaticInput (seq2seq's encoded source) pads to
    its request's length bucket, so its static shape differs per bucket
    — a fixed-width session cannot hold it. build_session must warn and
    return None (convoy fallback at startup), not 400 real requests."""
    from paddle_tpu.data import integer_value_sequence
    from paddle_tpu.models.seq2seq import seq2seq_attention

    dsl.reset()
    gen, data_names = seq2seq_attention(
        src_vocab=40, trg_vocab=40, embed_dim=8, hidden=8,
        beam_size=2, max_length=6, generating=True)
    graph = dsl.current_graph()
    from paddle_tpu.core.network import Network as Net
    net = Net(graph, outputs=["encoded", "encoded_proj", "decoder_boot"])
    params = dict(net.init_params(jax.random.PRNGKey(0)))
    for _, spec in get_layer_impl("beam_search_group").params(
            graph.layers["gen"], []).items():
        params.setdefault(spec.absolute_name,
                          jnp.zeros(spec.shape, jnp.float32))
    pred = ServingPredictor(
        graph, params, ["gen"], {"source_words": integer_value_sequence(40)},
        batch_buckets=[1], length_buckets=[4, 8], gen_decode_chunk=2)
    assert pred.build_session(2) is None
    eng = ServingEngine(pred, continuous_batching=True,
                        batch_timeout_ms=1.0).start(warmup=False)
    try:
        assert eng._session is None
        assert eng.continuous_batching is False  # stood down, warned
    finally:
        eng.shutdown()


def test_generate_traffic_does_not_starve_queued_score_requests():
    """Chunk-boundary admission must pause while a scoring request is
    queued: the session drains and the worker returns to _collect, so
    sustained generate traffic cannot deny service to /v1/score."""
    graph = _length_controlled_graph(48)
    params = _length_controlled_params(graph)
    pred = ServingPredictor(graph, params, ["gen", "boot"],
                            {"src": dense_vector(H)},
                            batch_buckets=[1, 2], gen_decode_chunk=2)
    eng = ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                        continuous_batching=True).start()
    try:
        # keep the session busy: a stream of long decodes...
        gens = [eng.submit(_long(), kind="generate") for _ in range(4)]
        score = eng.submit(_short(), kind="score")
        gens += [eng.submit(_long(), kind="generate") for _ in range(4)]
        assert score.event.wait(120.0), "score request starved"
        assert score.error is None
        for r in gens:
            assert r.event.wait(120.0)
            assert r.error is None
        assert eng.fatal is None
    finally:
        eng.shutdown()


def test_config_pinned_full_scan_reaches_serving_and_stands_down():
    """A config-pinned decode policy (``dsl.beam_search(full_scan=True)``)
    must flow through the predictor (no silent chunked override), and
    continuous batching — which needs chunk boundaries — must warn and
    stand down rather than ignore it. An explicit CLI-style
    ``gen_decode_chunk`` still overrides the pin."""
    dsl.reset()
    src = dsl.data("src", size=H)
    boot = dsl.fc(src, size=H, act="tanh", name="boot", bias_attr=False)

    def step(prev_emb):
        m = dsl.memory(name="h", size=H, boot_layer=boot)
        h = dsl.fc([prev_emb, m], size=H, act="tanh", name="h",
                   bias_attr=False)
        return dsl.fc(h, size=V, act="softmax", name="prob",
                      bias_attr=False)

    dsl.beam_search(
        step, [dsl.GeneratedInput(size=V, embedding_name="gen_emb",
                                  embedding_size=4)],
        bos_id=0, eos_id=EOS, beam_size=2, max_length=6, name="gen",
        full_scan=True)
    graph = dsl.current_graph()
    params = _length_controlled_params(graph)
    pred = ServingPredictor(graph, params, ["gen"],
                            {"src": dense_vector(H)}, batch_buckets=[1])
    assert pred.gen_effective_full_scan()
    pred.warmup()
    _, info = pred.generate_rows([_short()])
    assert info["decode_steps"] == 6  # full scan: no early exit
    assert pred.build_session(2) is None  # warn + convoy fallback
    # explicit override beats the pin
    pred2 = ServingPredictor(graph, params, ["gen"],
                             {"src": dense_vector(H)}, batch_buckets=[1],
                             gen_decode_chunk=2)
    assert not pred2.gen_effective_full_scan()
    pred2.warmup()
    _, info2 = pred2.generate_rows([_short()])
    assert info2["decode_steps"] < 6  # chunked early exit back on
    assert info2["steps_saved"] > 0


def test_prometheus_exports_decode_series(engines):
    cont, _ = engines
    text = cont.metrics.to_prometheus()
    assert "_decode_steps{quantile=" in text
    assert "_lane_occupancy " in text
    assert "_decode_chunks_total" in text
    assert "_continuous_admissions_total" in text
