"""BiLSTM-CRF tagging and seq2seq-attention NMT — the north-star sequence
models (`v1_api_demo/sequence_tagging/rnn_crf.py`, the seqToseq demo).

Generation goldens follow ``test_recurrent_machine_generation.cpp``:
fixed seeds -> fixed beams, checked against recorded sequences.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.optim import Adam, Momentum
from paddle_tpu.trainer import events as ev
from paddle_tpu.trainer.trainer import SGD

V_WORD, N_LABEL = 40, 5


def _tagging_reader(batches=6, seed=0):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(batches):
            B, T = 8, 10
            w = rng.randint(0, V_WORD, size=(B, T)).astype(np.int32)
            # learnable rule: label = word mod N_LABEL
            y = (w % N_LABEL).astype(np.int32)
            mask = np.ones((B, T), np.float32)
            yield {"word": Argument(value=jnp.asarray(w),
                                    mask=jnp.asarray(mask)),
                   "label": Argument(value=jnp.asarray(y),
                                     mask=jnp.asarray(mask))}

    return reader


def test_bilstm_crf_trains_and_decodes():
    from paddle_tpu.models import bilstm_crf_tagger
    dsl.reset()
    cost, decoded, _ = bilstm_crf_tagger(
        vocab_size=V_WORD, embed_dim=16, hidden=16, num_labels=N_LABEL)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=5e-3),
             extra_layers=[decoded])
    costs = []
    tr.train(_tagging_reader(), num_passes=8,
             event_handler=lambda e: costs.append(e.cost)
             if isinstance(e, ev.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.5

    # decode path: transitions shared with the cost layer by name
    assert "crf_transitions" in tr.params
    batch = next(iter(_tagging_reader(1)()))
    out = tr.forward(batch, output_names=["crf_decode"])["crf_decode"]
    path = np.asarray(out.value).reshape(8, 10)
    # after training, Viterbi should mostly recover word % N_LABEL
    want = np.asarray(batch["word"].value) % N_LABEL
    acc = float((path == want).mean())
    assert acc > 0.5, acc


def test_bilstm_crf_chunk_f1_via_evaluator():
    from paddle_tpu.models import bilstm_crf_tagger
    dsl.reset()
    cost, decoded, _ = bilstm_crf_tagger(
        vocab_size=V_WORD, embed_dim=16, hidden=16, num_labels=N_LABEL)
    graph = dsl.current_graph()
    graph.evaluators.append({
        "type": "chunk", "name": "chunk_f1",
        "input_layers": ["crf_decode", "label"],
        "_roles": {"n_outputs": 1, "has_label": True, "has_weight": False},
        "chunk_scheme": "IOB", "num_chunk_types": 2})
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=5e-3),
             extra_layers=[decoded])
    res = tr.test(_tagging_reader(2))
    assert "chunk_f1" in res.evaluator


# ------------------------------------------------------------------ NMT
def _nmt_reader(batches=8, seed=0, src_v=20, trg_v=12):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(batches):
            B, TS, TT = 8, 7, 6
            src = rng.randint(2, src_v, size=(B, TS)).astype(np.int32)
            # toy translation: target token = (src token + 1) mod trg_v
            trg_full = np.concatenate(
                [np.zeros((B, 1), np.int32),  # <s>
                 (src[:, :TT - 1] + 1) % trg_v], axis=1)
            trg_next = np.concatenate(
                [(src[:, :TT - 1] + 1) % trg_v,
                 np.ones((B, 1), np.int32)], axis=1)  # </s>
            m_s = np.ones((B, TS), np.float32)
            m_t = np.ones((B, TT), np.float32)
            yield {"source_words": Argument(value=jnp.asarray(src),
                                            mask=jnp.asarray(m_s)),
                   "target_words": Argument(value=jnp.asarray(trg_full),
                                            mask=jnp.asarray(m_t)),
                   "target_next": Argument(value=jnp.asarray(trg_next),
                                           mask=jnp.asarray(m_t))}

    return reader


def test_seq2seq_attention_trains():
    from paddle_tpu.models import seq2seq_attention
    dsl.reset()
    cost, probs, _ = seq2seq_attention(
        src_vocab=20, trg_vocab=12, embed_dim=16, hidden=16)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-2))
    costs = []
    tr.train(_nmt_reader(), num_passes=15,
             event_handler=lambda e: costs.append(e.cost)
             if isinstance(e, ev.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.6


def _gen_setup(seed=5):
    """Deterministic generation graph + params (no training): the golden
    fixture. Any change to beam search / attention / scan groups that
    alters results shows up as a golden mismatch."""
    from paddle_tpu.core.generation import SequenceGenerator
    from paddle_tpu.core.network import Network
    from paddle_tpu.models import seq2seq_attention
    dsl.reset()
    gen, _ = seq2seq_attention(src_vocab=20, trg_vocab=12, embed_dim=8,
                               hidden=8, beam_size=3, max_length=8,
                               generating=True)
    graph = dsl.current_graph()
    net = Network(graph, outputs=["encoded", "encoded_proj",
                                  "decoder_boot"])
    rng = np.random.RandomState(seed)
    params = {}
    for name, spec in net.param_specs.items():
        params[name] = jnp.asarray(
            rng.randn(*spec.shape).astype(np.float32) * 0.5)
    from paddle_tpu.core.registry import get_layer_impl
    impl = get_layer_impl("beam_search_group")
    for suffix, spec in impl.params(graph.layers["gen"], []).items():
        if spec.absolute_name not in params:
            params[spec.absolute_name] = jnp.asarray(
                rng.randn(*spec.shape).astype(np.float32) * 0.5)
    params["_trg_emb.w0"] = jnp.asarray(
        rng.randn(12, 8).astype(np.float32) * 0.5)
    src = np.array([[2, 5, 7, 9], [3, 4, 6, 8]], np.int32)
    feed = {"source_words": Argument(value=jnp.asarray(src),
                                     mask=jnp.ones((2, 4), jnp.float32))}
    outer = net.apply(params, feed, train=False)
    sg = SequenceGenerator(graph, "gen")
    return sg, params, outer


def test_seq2seq_beam_generation_golden():
    sg, params, outer = _gen_setup()
    tokens, scores, lengths = sg.generate(params, outer)
    tokens = np.asarray(tokens)
    scores = np.asarray(scores)
    assert tokens.shape[0] == 2 and tokens.shape[1] == 3
    # beams are sorted best-first and deterministic
    assert np.all(np.diff(scores, axis=1) <= 1e-6)
    # golden: regenerate with _gen_setup(seed=5) if the kernel math
    # intentionally changes
    golden_first = tokens[:, 0, :].tolist()
    assert golden_first == GOLDEN_BEST_BEAMS, golden_first
    # repeatable: same params, same beams
    t2, _, _ = sg.generate(params, outer)
    assert np.array_equal(tokens, np.asarray(t2))


def test_seq2seq_greedy_is_beam1():
    sg, params, outer = _gen_setup()
    t1, s1, l1 = sg.generate(params, outer, beam_size=1)
    tb, sb, lb = sg.generate(params, outer, beam_size=3)
    # the best of a wider beam scores at least as well as greedy
    assert np.all(np.asarray(sb)[:, 0] >= np.asarray(s1)[:, 0] - 1e-5)


# Recorded from _gen_setup(seed=5) — the test_recurrent_machine_generation
# golden-file pattern, inlined.
GOLDEN_BEST_BEAMS = [[9, 0, 9, 9, 9, 9, 5, 0],
                     [9, 11, 6, 7, 5, 0, 9, 6]]
