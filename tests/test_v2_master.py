"""v2 master-client surface over the in-proc master server — the
reference's `python/paddle/v2/master/client.py` + `creator.cloud_reader`
path (etcd discovery absorbed by the master address, SURVEY §5.8)."""

import pytest

from paddle_tpu.data.recordio import write_chunk
from paddle_tpu.dist.master import MasterServer, MasterService


@pytest.fixture()
def served_chunks(tmp_path):
    paths = []
    for i in range(3):
        p = str(tmp_path / f"chunk-{i:03d}")
        write_chunk(p, [f"rec-{i}-{j}" for j in range(4)])
        paths.append(p)
    svc = MasterService(timeout_s=30.0, chunks_per_task=1)
    server = MasterServer(svc).start()
    yield server, paths
    server.stop()


def test_v2_client_streams_all_records_then_pass_end(served_chunks):
    from paddle_tpu.v2 import master
    server, paths = served_chunks
    c = master.client("%s:%d" % server.addr)
    c.set_dataset(paths)
    c.paddle_start_get_records(0)
    got = []
    while True:
        r, e = c.next_record()
        if e != master.OK:
            assert e == master.PASS_END
            break
        got.append(r)
    assert sorted(got) == sorted(f"rec-{i}-{j}"
                                 for i in range(3) for j in range(4))
    # PASS_END latches: further calls must NOT silently restart pass 0
    assert c.next_record() == (None, master.PASS_END)
    assert c.next_record() == (None, master.PASS_END)
    # explicitly starting the next pass streams again
    c.paddle_start_get_records(1)
    r, e = c.next_record()
    assert e == master.OK and r.startswith("rec-")
    c.release()


def test_v2_client_save_arbitration(served_chunks):
    from paddle_tpu.v2 import master
    server, paths = served_chunks
    c1 = master.client("%s:%d" % server.addr)
    c2 = master.client("%s:%d" % server.addr)
    assert c1.request_save_model("t0", 60000) == 1
    assert c2.request_save_model("t1", 60000) == 0  # other trainer saving
    c1.release(), c2.release()


def test_cloud_reader_round(served_chunks):
    import paddle_tpu.v2 as paddle
    server, paths = served_chunks
    reader = paddle.reader.creator.cloud_reader(
        paths, "%s:%d" % server.addr)
    assert len(list(reader())) == 12
    assert len(list(reader())) == 12  # second call = next pass
