"""Optimizer semantics tests — the analogue of
``paddle/math/tests/test_TrainingAlgorithm.cpp``, which checks the fused
kernels against reference implementations (``OriginalOptimizerApi.h``):
here each Optimizer is checked against a hand-written numpy step of the
formulas in TrainingAlgorithmOp.cu."""

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.optim import (AdaDelta, AdaGrad, Adam, Adamax,
                              DecayedAdaGrad, Momentum, RMSProp,
                              create_optimizer)


def _run(opt, p0, grads_seq):
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params)
    for g in grads_seq:
        params, state = opt.update({"w": jnp.asarray(g)}, state, params,
                                   batch_size=4)
    return np.asarray(params["w"]), state


def test_momentum_matches_reference_formula():
    p0 = np.array([1.0, -2.0, 3.0], np.float32)
    gs = [np.array([0.1, 0.2, -0.3], np.float32),
          np.array([-0.05, 0.1, 0.2], np.float32)]
    lr, mu, decay = 0.1, 0.9, 0.01
    opt = Momentum(learning_rate=lr, momentum=mu, l2_rate=decay)
    got, _ = _run(opt, p0, gs)
    # sgdUpdate: mom = mu*mom - lr*(g + decay*p); p += mom
    p, mom = p0.copy(), np.zeros_like(p0)
    for g in gs:
        mom = mu * mom - lr * (g + decay * p)
        p = p + mom
    np.testing.assert_allclose(got, p, rtol=1e-6)


def test_adagrad_formula():
    p0 = np.array([0.5, -0.5], np.float32)
    gs = [np.array([0.3, -0.1], np.float32),
          np.array([0.2, 0.4], np.float32)]
    opt = AdaGrad(learning_rate=0.1, epsilon=1e-6)
    got, _ = _run(opt, p0, gs)
    p, accum, mom = p0.copy(), np.zeros_like(p0), np.zeros_like(p0)
    for g in gs:
        accum = accum + g * g
        lr_vec = 1.0 / np.sqrt(accum + 1e-6)
        mom = 0.0 * mom - 0.1 * lr_vec * g
        p = p + mom
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_adam_formula():
    p0 = np.array([1.0, 2.0], np.float32)
    gs = [np.array([0.1, -0.2], np.float32)] * 3
    b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.01
    opt = Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps)
    got, _ = _run(opt, p0, gs)
    p, m, v = p0.copy(), np.zeros_like(p0), np.zeros_like(p0)
    for t, g in enumerate(gs, start=1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        alpha = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        p = p - alpha * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_rmsprop_formula():
    p0 = np.array([0.3, -0.7], np.float32)
    gs = [np.array([0.2, 0.1], np.float32),
          np.array([-0.1, 0.3], np.float32)]
    rou, eps, lr = 0.95, 1e-6, 0.05
    opt = RMSProp(learning_rate=lr, rou=rou, epsilon=eps)
    got, _ = _run(opt, p0, gs)
    p = p0.copy()
    G = np.zeros_like(p0); F = np.zeros_like(p0); mom = np.zeros_like(p0)
    for g in gs:
        G = rou * G + (1 - rou) * g * g
        F = rou * F + (1 - rou) * g
        scale = 1.0 / np.sqrt(G - F * F + eps)
        mom = 0.0 * mom - lr * scale * g
        p = p + mom
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_l1_shrink():
    opt = Momentum(learning_rate=0.1, l1_rate=0.5)
    p0 = np.array([0.04, -0.03, 1.0], np.float32)
    got, _ = _run(opt, p0, [np.zeros(3, np.float32)])
    # after zero-grad step, |p| shrinks by l1*lr = 0.05, clamped at 0
    np.testing.assert_allclose(got, [0.0, 0.0, 0.95], atol=1e-6)


def test_static_params_skipped():
    opt = Momentum(learning_rate=1.0)
    from paddle_tpu.core.registry import ParamSpec
    params = {"w": jnp.ones(3), "frozen": jnp.ones(3)}
    meta = {"w": ParamSpec(shape=(3,)),
            "frozen": ParamSpec(shape=(3,), is_static=True)}
    state = opt.init(params, meta)
    assert "frozen" not in state["slots"]
    new_p, _ = opt.update({"w": jnp.ones(3), "frozen": jnp.ones(3)},
                          state, params, meta)
    np.testing.assert_allclose(np.asarray(new_p["frozen"]), 1.0)
    assert not np.allclose(np.asarray(new_p["w"]), 1.0)


def test_lr_schedules():
    from paddle_tpu.optim.schedules import learning_rate_at
    assert float(learning_rate_at("constant", 0.1, 0, 0, 100)) == pytest.approx(0.1)
    assert float(learning_rate_at("poly", 0.1, 0.01, 0.5, 100)) == pytest.approx(
        0.1 * (1 + 0.01 * 100) ** -0.5)
    assert float(learning_rate_at("linear", 0.1, 1e-4, 0.01, 500)) == pytest.approx(
        0.1 - 1e-4 * 500)
    assert float(learning_rate_at("discexp", 0.1, 0.5, 100, 250)) == pytest.approx(
        0.1 * 0.5 ** 2)


def test_factory():
    assert isinstance(create_optimizer("adam", learning_rate=0.1), Adam)
    assert isinstance(create_optimizer("sgd"), Momentum)
    with pytest.raises(KeyError):
        create_optimizer("nope")


def test_model_averaging():
    opt = Momentum(learning_rate=0.1, average_window=2.0)
    p0 = np.array([1.0], np.float32)
    got, state = _run(opt, p0, [np.array([1.0], np.float32)] * 3)
    assert "avg" in state
    assert np.isfinite(np.asarray(state["avg"]["w"])).all()


def test_manual_schedule_piecewise():
    from paddle_tpu.optim.schedules import learning_rate_at
    # boundaries at 100 and 200 samples; factors 1.0 / 0.5 / 0.1
    lr = learning_rate_at("manual", 0.2, 0, 0, 50, args="100:1.0,200:0.5,300:0.1")
    np.testing.assert_allclose(float(lr), 0.2, rtol=1e-6)
    lr = learning_rate_at("manual", 0.2, 0, 0, 150, args="100:1.0,200:0.5,300:0.1")
    np.testing.assert_allclose(float(lr), 0.1, rtol=1e-6)
    lr = learning_rate_at("manual", 0.2, 0, 0, 999, args="100:1.0,200:0.5,300:0.1")
    np.testing.assert_allclose(float(lr), 0.02, rtol=1e-6)


def test_pass_manual_schedule():
    from paddle_tpu.optim.schedules import learning_rate_at
    lr = learning_rate_at("pass_manual", 1.0, 0, 0, 0,
                          args="1:1.0,2:0.5", num_passes=0)
    assert float(lr) == 1.0
    lr = learning_rate_at("pass_manual", 1.0, 0, 0, 0,
                          args="1:1.0,2:0.5", num_passes=5)
    assert float(lr) == 0.5


def test_nesterov_momentum_differs_and_converges():
    p0 = np.array([1.0, -1.0], np.float32)
    gs = [p0.copy() * 0.5] * 5
    plain, _ = _run(Momentum(learning_rate=0.1, momentum=0.9), p0, gs)
    nest, _ = _run(Momentum(learning_rate=0.1, momentum=0.9, nesterov=True),
                   p0, gs)
    assert not np.allclose(plain, nest)


def test_model_averaging_apply():
    opt = Momentum(learning_rate=0.5, average_window=10)
    params = {"w": jnp.asarray(np.array([0.0], np.float32))}
    state = opt.init(params)
    for _ in range(4):
        params, state = opt.update(
            {"w": jnp.asarray(np.array([1.0], np.float32))}, state, params)
    avg = opt.averaged_params(state, params)
    # averaged value lags the raw trained value (running mean of iterates)
    assert float(avg["w"][0]) > float(params["w"][0])
    assert float(avg["w"][0]) < 0.0  # moved in the gradient direction


def test_model_averaging_fractional_window_is_not_a_noop():
    """The reference's average_window is a FRACTION of updates so far
    (TrainerConfig.proto:70-74; ModelAverage(average_window=0.5) is the
    normal v1 usage) — the averaged params must lag the raw iterates,
    not equal them."""
    opt = Momentum(learning_rate=0.5, average_window=0.5)
    params = {"w": jnp.asarray(np.array([0.0], np.float32))}
    state = opt.init(params)
    for _ in range(8):
        params, state = opt.update(
            {"w": jnp.asarray(np.array([1.0], np.float32))}, state, params)
    avg = opt.averaged_params(state, params)
    assert float(avg["w"][0]) > float(params["w"][0]) + 1e-4  # lags
    assert float(avg["w"][0]) < 0.0


def test_update_with_partial_grads_keeps_other_slots():
    """An update carrying gradients for a SUBSET of parameters must not
    erase the others' optimizer state (momentum history stays intact and
    later full updates keep working)."""
    opt = Momentum(learning_rate=0.1, momentum=0.9)
    params = {"a": jnp.zeros(2), "b": jnp.zeros(2)}
    state = opt.init(params)
    g = jnp.ones(2)
    params, state = opt.update({"a": g, "b": g}, state, params)
    mom_b = np.asarray(state["slots"]["b"]["mom"]).copy()
    params, state = opt.update({"a": g}, state, params)  # subset
    assert "b" in state["slots"], "b's slots erased by a partial update"
    np.testing.assert_allclose(np.asarray(state["slots"]["b"]["mom"]),
                               mom_b)
    params2, state = opt.update({"a": g, "b": g}, state, params)
    assert float(params2["b"][0]) != float(params["b"][0])  # still trains


def test_static_pruning_hook_keeps_weights_zero():
    """StaticPruningHook (ParameterUpdaterHook.cpp:39): the smallest-|w|
    fraction is masked at init and stays exactly zero through updates."""
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.core.registry import ParamSpec
    from paddle_tpu.optim.optimizers import Momentum

    rng = np.random.RandomState(0)
    p0 = rng.randn(16, 8).astype(np.float32)
    meta = {"w": ParamSpec(shape=(16, 8), sparsity_ratio=0.5)}
    opt = Momentum(learning_rate=0.1, momentum=0.9)
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params, meta)
    mask = np.asarray(state["slots"]["w"]["prune_mask"])
    assert abs(mask.mean() - 0.5) < 0.1  # ~half pruned
    for _ in range(5):
        g = jnp.asarray(rng.randn(16, 8).astype(np.float32))
        params, state = opt.update({"w": g}, state, params, meta,
                                   batch_size=4)
    w = np.asarray(params["w"])
    assert np.all(w[mask == 0] == 0.0)      # pruned stay zero
    assert np.any(w[mask == 1] != p0[mask == 1])  # others trained


def test_pruning_hook_via_v1_config_attr():
    """ParameterAttribute(update_hooks=HookAttribute('pruning', r)) flows
    through the compat surface into the engine ParamSpec."""
    from paddle_tpu.compat.trainer_config_helpers.attrs import (
        HookAttribute, ParameterAttribute)
    attr = ParameterAttribute(
        update_hooks=HookAttribute("pruning", sparsity_ratio=0.7))
    assert attr.to_param_attr().sparsity_ratio == 0.7
