"""Fast deterministic chaos subset (tier-1; the multi-process soak is
``tools/chaos_soak.py`` / test_chaos_soak.py, marked slow).

Every fault here is injected through the REAL hook points in production
code — the master RPC codec, the checkpoint writer, the trainer step
loop — by a seeded ``testing.chaos.FaultPlan``, so what is tested is
the recovery machinery itself: RPC retry + idempotent dedupe under
message loss, corrupted-generation fallback, and the crown guarantee —
a master-fed trainer killed mid-run auto-resumes BITWISE onto the
uninterrupted trajectory via the checkpoint's task ledger and
``resume_lease``.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.dist import (MasterClient, MasterServer, MasterService,
                             master_reader)
from paddle_tpu.dist.checkpoint import Checkpointer
from paddle_tpu.optim import Adam
from paddle_tpu.testing.chaos import (ChaosKilled, FaultPlan, chaos_plan,
                                      install_from_env)
from paddle_tpu.trainer import SGD

pytestmark = pytest.mark.chaos

WIDTH, CLASSES, B = 8, 3, 8
BATCHES, PASSES = 4, 2


# ------------------------------------------------------------ FaultPlan

def test_plan_is_deterministic_and_roundtrips_env():
    faults = [{"type": "drop", "site": "msg_send", "rate": 0.3},
              {"type": "kill", "site": "step", "at": 5, "mode": "raise"}]
    a, b = FaultPlan(seed=9, faults=faults), FaultPlan(seed=9, faults=faults)
    for n in range(1, 50):
        assert a._matches(0, faults[0], "msg_send", n) == \
            b._matches(0, faults[0], "msg_send", n)
    # a different seed produces a different Bernoulli schedule
    c = FaultPlan(seed=10, faults=faults)
    assert any(a._matches(0, faults[0], "msg_send", n)
               != c._matches(0, faults[0], "msg_send", n)
               for n in range(1, 200))
    os.environ["PADDLE_TPU_CHAOS_PLAN"] = a.to_json()
    try:
        got = install_from_env()
        assert got is not None and got.seed == 9 and got.faults == faults
    finally:
        del os.environ["PADDLE_TPU_CHAOS_PLAN"]
        from paddle_tpu.testing import chaos
        chaos.install(None)


def test_plan_triggers_combine_as_conjunction():
    """Triggers in one fault are combinable (docstring contract): every
    present trigger must agree, not first-key-wins — {"every": 2,
    "after": 2} fires on even hits within the window only, and adding
    "rate" gates those same hits through the seeded coin flip."""
    f = {"type": "drop", "site": "msg_send", "after": 2, "count": 10,
         "every": 2}
    plan = FaultPlan(seed=3, faults=[f])
    fired = [n for n in range(1, 20) if plan._matches(0, f, "msg_send", n)]
    assert fired == [4, 6, 8, 10, 12]
    g = dict(f, rate=0.5)
    gated = FaultPlan(seed=3, faults=[g])
    sub = [n for n in range(1, 20) if gated._matches(0, g, "msg_send", n)]
    assert set(sub) <= set(fired) and sub != fired  # a strict, seeded subset
    assert [n for n in range(1, 20)
            if FaultPlan(seed=3, faults=[g])._matches(0, g, "msg_send", n)]         == sub  # still seed-reproducible


def test_zero_cost_when_disabled():
    from paddle_tpu.testing import chaos
    assert chaos._ACTIVE is None  # the guard every hook site polls


# --------------------------------------------------- RPC under fire

def test_message_loss_is_at_least_once_exactly_delivered():
    """15% of RPC messages dropped (both directions, deterministic
    seed): the client redials with jittered backoff, get_task re-serves
    the caller's lease idempotently, task_finished dedupes — one pass
    delivers every record exactly once, no spurious failures."""
    svc = MasterService(timeout_s=30.0, failure_max=50, chunks_per_task=1)
    server = MasterServer(svc).start()
    plan = FaultPlan(seed=3, faults=[
        {"type": "drop", "site": "msg_recv", "rate": 0.15},
        {"type": "delay", "site": "msg_send", "every": 11,
         "seconds": 0.002}])
    try:
        client = MasterClient(server.addr, retries=40, retry_delay=0.01,
                              backoff_cap=0.05, trainer_id="tr-drop")
        client.set_dataset([[i] for i in range(12)])
        with chaos_plan(plan):
            got = list(master_reader(client, lambda c: c)())
        assert sorted(got) == list(range(12))
        assert any(t == "drop" for _, _, t in plan.log), \
            "the plan never actually fired"
        assert not svc.failed and not svc.pending
        client.close()
    finally:
        server.stop()


# ------------------------------------------- corrupted generations

def _fake_state(seed):
    rng = np.random.RandomState(seed)
    return ({"w": rng.randn(3, 3).astype(np.float32)},
            {"slots": {"w": {"mom": rng.randn(3, 3).astype(np.float32)}}})


@pytest.mark.parametrize("mode", ["truncate", "bitflip", "bitflip_meta",
                                  "delete_meta"])
def test_plan_corrupts_latest_restore_falls_back(tmp_path, mode):
    """A FaultPlan corrupting the 2nd durable generation (each mode of
    mutilation) leaves restore on the previous INTACT one — never a
    crash, never torn state."""
    ck = Checkpointer(str(tmp_path), keep=3)
    plan = FaultPlan(seed=0, faults=[
        {"type": "corrupt", "site": "checkpoint", "at": 2, "mode": mode}])
    with chaos_plan(plan):
        for p in range(2):
            params, opt = _fake_state(p)
            ck.save(params, opt, pass_id=p)
    restored = ck.restore()
    assert restored is not None
    params, _, meta = restored
    assert meta["pass_id"] == 0
    np.testing.assert_array_equal(params["w"], _fake_state(0)[0]["w"])


# ------------------------------------- the crown: master-fed resume

def _batches():
    rng = np.random.RandomState(13)
    X = rng.randn(BATCHES * B, WIDTH).astype(np.float32)
    W = rng.randn(WIDTH, CLASSES)
    Y = np.argmax(X @ W, axis=1).astype(np.int32)
    feeds = []
    for i in range(0, BATCHES * B, B):
        feeds.append({"x": Argument(value=jnp.asarray(X[i:i + B])),
                      "label": Argument(value=jnp.asarray(Y[i:i + B]))})
    return feeds


def _build(seed=21):
    dsl.reset()
    x = dsl.data(name="x", size=WIDTH)
    lbl = dsl.data(name="label", size=CLASSES)
    h = dsl.fc(input=x, size=WIDTH, act="tanh")
    h = dsl.dropout(input=h, rate=0.25)
    out = dsl.fc(input=h, size=CLASSES, act="softmax")
    cost = dsl.classification_cost(input=out, label=lbl)
    return SGD(cost=cost, update_equation=Adam(learning_rate=3e-3),
               seed=seed)


def _final(tr):
    return {k: np.asarray(jax.device_get(v)) for k, v in tr.params.items()}


@pytest.mark.parametrize("kill_at,site", [(5, "step_done"), (7, "step")],
                         ids=["after_ckpt_p1b0", "before_ckpt_p1b2"])
def test_master_fed_kill_resume_bitwise(tmp_path, kill_at, site):
    """A trainer reading from a live master, killed mid-run, resumes
    bitwise onto the clean trajectory: the checkpoint's task ledger +
    ``resume_lease`` re-mark consumed tasks done, requeue this
    trainer's post-checkpoint work IN ORDER, and skip the in-flight
    task's already-trained prefix. The master survives the whole drama
    in-process (only the trainer 'dies')."""
    feeds = _batches()

    # clean trajectory: a plain reader over the same batch sequence
    clean = _build()
    clean.train(lambda: iter(feeds), num_passes=PASSES)
    want = _final(clean)

    svc = MasterService(timeout_s=30.0, failure_max=50, chunks_per_task=1)
    server = MasterServer(svc).start()
    try:
        def load_chunk(i):
            yield feeds[i]

        def make_reader():
            # same trainer identity across "process" restarts, like
            # dist/launch.py's trainer-{process_id}
            client = MasterClient(server.addr, trainer_id="tr-0",
                                  retries=20, retry_delay=0.01)
            client.set_dataset(list(range(BATCHES)))
            return master_reader(client, load_chunk)

        plan = FaultPlan(seed=0, faults=[
            {"type": "kill", "site": site, "at": kill_at,
             "mode": "raise"}])
        ck_a = Checkpointer(str(tmp_path), saving_period=1,
                            saving_period_by_batches=2, background=True)
        run_a = _build()
        with chaos_plan(plan):
            with pytest.raises(ChaosKilled):
                run_a.train(make_reader(), num_passes=PASSES,
                            checkpointer=ck_a)
        ck_a.flush()

        run_b = _build()
        run_b.train(make_reader(), num_passes=PASSES,
                    checkpointer=Checkpointer(
                        str(tmp_path), saving_period=1,
                        saving_period_by_batches=2, background=True))
        got = _final(run_b)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k], err_msg=k)
        # the ledger really committed: the master holds no stale state
        assert not svc.pending and not svc.todo
    finally:
        server.stop()


def test_master_killed_and_recovered_mid_run(tmp_path):
    """The MASTER dies mid-pass instead: a new MasterService recovers
    from the FileStore snapshot (in-flight + uncommitted work requeued
    in order), the trainer's client redials, and the job still ends
    with every task resolved and the bitwise-clean parameters."""
    from paddle_tpu.dist import FileStore

    feeds = _batches()
    clean = _build()
    clean.train(lambda: iter(feeds), num_passes=PASSES)
    want = _final(clean)

    snap = str(tmp_path / "master.snap")
    svc = MasterService(store=FileStore(snap), timeout_s=30.0,
                        failure_max=50, chunks_per_task=1)
    server = MasterServer(svc).start()
    addr_holder = {"addr": server.addr}

    def load_chunk(i):
        yield feeds[i]

    client = MasterClient(addr_holder["addr"], trainer_id="tr-0",
                          retries=60, retry_delay=0.02, backoff_cap=0.2)
    client.set_dataset(list(range(BATCHES)))
    reader = master_reader(client, load_chunk)

    killed = {"done": False}

    def handler(e):
        # kill + restart the master right after pass 0 batch 1, while
        # tasks are mid-flight — on the SAME port (the client redials)
        from paddle_tpu.trainer import events as ev
        if (not killed["done"] and isinstance(e, ev.EndIteration)
                and e.pass_id == 0 and e.batch_id == 1):
            killed["done"] = True
            host, port = addr_holder["addr"]
            server.stop()
            svc2 = MasterService(store=FileStore(snap), timeout_s=30.0,
                                 failure_max=50, chunks_per_task=1)
            new_server = MasterServer(svc2, host=host, port=port).start()
            addr_holder["server"] = new_server

    tr = _build()
    try:
        tr.train(reader, num_passes=PASSES,
                 checkpointer=Checkpointer(str(tmp_path / "ck"),
                                           saving_period=1,
                                           saving_period_by_batches=2),
                 event_handler=handler)
    finally:
        srv = addr_holder.get("server")
        if srv is not None:
            srv.stop()
        client.close()
    assert killed["done"], "the mid-run master kill never happened"
    got = _final(tr)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)


def test_triggerless_fault_fires_on_every_hit():
    """The empty conjunction is TRUE: {"type": "drop", "site": s} with no
    at/after/every/rate means "drop every arrival at s" — it must not be
    silently inert (a fault-free soak would pass with zero injection,
    faking fault-tolerance coverage)."""
    f = {"type": "drop", "site": "msg_send"}
    plan = FaultPlan(seed=0, faults=[f])
    assert all(plan._matches(0, f, "msg_send", n) for n in range(1, 20))
    assert not plan._matches(0, f, "msg_recv", 1)   # site still gates
