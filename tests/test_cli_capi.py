"""Trainer CLI + merged-model + C inference API tests.

CLI mirrors `paddle/trainer/tests/test_Trainer.cpp` (run a real config a
pass, assert cost) and `--job=checkgrad/time` modes; the capi test
compiles and runs an actual C program against the shim, the analogue of
`paddle/capi/tests`.
"""

import ctypes
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.trainer import cli

CONFIG = textwrap.dedent("""
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.data.types import dense_vector, integer_value
    from paddle_tpu.optim import Momentum

    x = dsl.data(name="x", size=8)
    lab = dsl.data(name="label", size=4)
    hid = dsl.fc(input=x, size=16, act="relu")
    out = dsl.fc(input=hid, size=4, act="softmax")
    cost = dsl.classification_cost(input=out, label=lab)
    outputs = [out]
    optimizer = Momentum(learning_rate=lr, momentum=0.9)
    feeding = {"x": dense_vector(8), "label": integer_value(4)}

    _rng = np.random.RandomState(0)
    _X = _rng.randn(128, 8).astype(np.float32)
    _Y = np.argmax(_X[:, :4], axis=1)

    def train_reader():
        for i in range(0, 128, 32):
            yield [(_X[j], int(_Y[j])) for j in range(i, i + 32)]

    test_reader = train_reader
""")


@pytest.fixture()
def config_file(tmp_path):
    path = tmp_path / "conf.py"
    path.write_text(CONFIG)
    return str(path)


def test_cli_train_test_merge(config_file, tmp_path, capsys):
    save = str(tmp_path / "ckpt")
    rc = cli.main(["--config", config_file, "--config_args", "lr=0.1",
                   "--job", "train", "--num_passes", "4",
                   "--save_dir", save, "--log_period", "0"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Pass 3:" in out
    rc = cli.main(["--config", config_file, "--config_args", "lr=0.1",
                   "--job", "test", "--save_dir", save])
    assert rc == 0
    assert "Test: cost=" in capsys.readouterr().out
    model = str(tmp_path / "m.ptmodel")
    rc = cli.main(["--config", config_file, "--config_args", "lr=0.1",
                   "--job", "merge", "--save_dir", save,
                   "--model_path", model])
    assert rc == 0 and os.path.exists(model)
    # merged model loads and predicts
    from paddle_tpu.capi import host
    mid = host.load(model)
    x = np.zeros((2, 8), dtype="<f4")
    payload, rows, cols = host.infer_raw(mid, None, x.tobytes(), 2, 8)
    assert (rows, cols) == (2, 4)
    probs = np.frombuffer(payload, "<f4").reshape(2, 4)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)
    host.release(mid)


def test_cli_checkgrad(config_file, capsys):
    rc = cli.main(["--config", config_file, "--config_args", "lr=0.1",
                   "--job", "checkgrad"])
    assert rc == 0
    assert "checkgrad PASSED" in capsys.readouterr().out


def test_cli_time(config_file, capsys):
    rc = cli.main(["--config", config_file, "--config_args", "lr=0.1",
                   "--job", "time", "--time_batches", "3",
                   "--time_warmup", "1"])
    assert rc == 0
    assert "avg_batch_time=" in capsys.readouterr().out


def test_capi_from_c_program(config_file, tmp_path):
    """Compile a real C program against the shim and run inference."""
    from paddle_tpu import capi
    save = str(tmp_path / "ckpt")
    model = str(tmp_path / "m.ptmodel")
    assert cli.main(["--config", config_file, "--config_args", "lr=0.1",
                     "--job", "train", "--num_passes", "1",
                     "--save_dir", save, "--log_period", "0"]) == 0
    assert cli.main(["--config", config_file, "--config_args", "lr=0.1",
                     "--job", "merge", "--save_dir", save,
                     "--model_path", model]) == 0
    so = capi.build_library()

    c_src = tmp_path / "main.c"
    c_src.write_text(textwrap.dedent("""
        #include <stdio.h>
        #include "paddle_tpu_capi.h"
        int main(int argc, char** argv) {
            if (ptc_init(NULL) != 0) {
                fprintf(stderr, "init: %s\\n", ptc_last_error());
                return 1;
            }
            void* m = ptc_load(argv[1]);
            if (!m) {
                fprintf(stderr, "load: %s\\n", ptc_last_error());
                return 2;
            }
            float in[16]; int i;
            for (i = 0; i < 16; i++) in[i] = 0.25f * (i % 5);
            float out[8]; int rows, cols;
            if (ptc_infer(m, "x", in, 2, 8, out, 8, &rows, &cols) != 0) {
                fprintf(stderr, "infer: %s\\n", ptc_last_error());
                return 3;
            }
            printf("rows=%d cols=%d\\n", rows, cols);
            float s = 0; for (i = 0; i < cols; i++) s += out[i];
            printf("row0_sum=%.4f\\n", s);
            ptc_release(m);
            return 0;
        }
    """))
    exe = str(tmp_path / "capi_demo")
    inc = os.path.join(os.path.dirname(capi.__file__), "include")
    subprocess.run(["gcc", "-o", exe, str(c_src), f"-I{inc}", so,
                    f"-Wl,-rpath,{os.path.dirname(so)}"],
                   check=True, capture_output=True)
    # embedders provide the package path via PYTHONPATH (the shim doesn't
    # assume a venv)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pypath = ":".join([repo_root]
                      + [p for p in sys.path if "site-packages" in p])
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=pypath)
    res = subprocess.run([exe, model], capture_output=True, text=True,
                         timeout=300, env=env)
    assert res.returncode == 0, res.stderr
    assert "rows=2 cols=4" in res.stdout
    row0_sum = float(res.stdout.split("row0_sum=")[1].split()[0])
    assert abs(row0_sum - 1.0) < 1e-3  # softmax row sums to 1


def test_inference_uses_layer_graph_after_reset():
    """Layers remember their graph: inference on model A keeps working
    after dsl.reset() started building model B."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.config import dsl
    dsl.reset()
    a_in = paddle.layer.data(name="xa",
                             type=paddle.data_type.dense_vector(4))
    a_out = paddle.layer.fc(input=a_in, size=3,
                            act=paddle.activation.Softmax())
    tr = paddle.trainer.SGD(
        cost=paddle.layer.classification_cost(
            input=a_out, label=paddle.layer.data(
                name="la", type=paddle.data_type.integer_value(3))),
        update_equation=paddle.optimizer.Momentum(learning_rate=0.1))
    params = paddle.Parameters.from_trainer(tr)
    # now a different model occupies the global graph
    dsl.reset()
    paddle.layer.data(name="other", type=paddle.data_type.dense_vector(7))
    pred = paddle.infer(
        output_layer=a_out, parameters=params,
        input=[([0.1, 0.2, 0.3, 0.4],)],
        feeding={"xa": paddle.data_type.dense_vector(4)})
    assert pred.shape == (1, 3)
