"""End-to-end smoke: build small nets with the DSL, train a few steps, and
verify cost decreases — the shape of the reference's
``test_TrainerOnePass.cpp`` assertions."""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.config import dsl
from paddle_tpu.data import DataFeeder, dense_vector, integer_value
from paddle_tpu.optim import Momentum, Adam
from paddle_tpu.trainer import SGD


def _toy_classification(n=256, dim=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim, classes)
    x = rng.randn(n, dim).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.randn(n, classes), axis=1)
    return x, y.astype(np.int64)


def _batches(x, y, bs):
    def reader():
        for i in range(0, len(x), bs):
            yield [(x[j], int(y[j])) for j in range(i, min(i + bs, len(x)))]
    return reader


def test_mlp_trains():
    dsl.reset()
    img = dsl.data(name="x", size=8)
    lab = dsl.data(name="label", size=4)
    h = dsl.fc(input=img, size=32, act="relu")
    out = dsl.fc(input=h, size=4, act="softmax")
    cost = dsl.classification_cost(input=out, label=lab)

    trainer = SGD(cost=cost, update_equation=Momentum(
        learning_rate=0.1, momentum=0.9))
    x, y = _toy_classification()
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})

    costs = []
    trainer.train(_batches(x, y, 64), feeder=feeder, num_passes=8,
                  event_handler=lambda e: costs.append(e.cost)
                  if hasattr(e, "cost") else None)
    assert costs[0] > costs[-1], (costs[0], costs[-1])
    assert costs[-1] < 0.7 * costs[0]

    res = trainer.test(_batches(x, y, 64), feeder=feeder)
    assert res.evaluator["classification_error"] < 0.25


def test_regression_mse():
    dsl.reset()
    x_l = dsl.data(name="x", size=4)
    y_l = dsl.data(name="y", size=1)
    pred = dsl.fc(input=x_l, size=1, act="linear")
    cost = dsl.square_error_cost(input=pred, label=y_l)

    rng = np.random.RandomState(1)
    w = rng.randn(4, 1)
    x = rng.randn(512, 4).astype(np.float32)
    y = (x @ w).astype(np.float32)

    def reader():
        for i in range(0, len(x), 128):
            yield [(x[j], y[j]) for j in range(i, min(i + 128, len(x)))]

    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=0.05))
    feeder = DataFeeder({"x": dense_vector(4), "y": dense_vector(1)})
    costs = []
    trainer.train(reader, feeder=feeder, num_passes=20,
                  event_handler=lambda e: costs.append(e.cost)
                  if hasattr(e, "cost") else None)
    assert costs[-1] < 0.05 * costs[0]


def test_lstm_sequence_classification():
    dsl.reset()
    # variable-length sequences of token ids; class = parity of max token
    vocab, emb, hidden, classes = 20, 16, 32, 2
    words = dsl.data(name="words", size=vocab, is_sequence=True)
    lab = dsl.data(name="label", size=classes)
    e = dsl.embedding(input=words, size=emb, vocab_size=vocab)
    proj = dsl.fc(input=e, size=hidden * 4, act="linear")
    lstm = dsl.lstmemory(input=proj)
    pooled = dsl.pooling(input=lstm, pooling_type="max")
    out = dsl.fc(input=pooled, size=classes, act="softmax")
    cost = dsl.classification_cost(input=out, label=lab)

    rng = np.random.RandomState(2)
    data = []
    for _ in range(256):
        L = rng.randint(3, 12)
        seq = rng.randint(0, vocab, size=L)
        data.append((list(seq), int(seq.max() % 2)))

    from paddle_tpu.data import integer_value_sequence
    feeder = DataFeeder({"words": integer_value_sequence(vocab),
                         "label": integer_value(classes)}, pad_multiple=16)

    def reader():
        for i in range(0, len(data), 64):
            yield data[i:i + 64]

    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=0.01))
    costs = []
    trainer.train(reader, feeder=feeder, num_passes=12,
                  event_handler=lambda e: costs.append(e.cost)
                  if hasattr(e, "cost") else None)
    assert costs[-1] < 0.8 * costs[0], (costs[0], costs[-1])
