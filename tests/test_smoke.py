"""End-to-end smoke: build small nets with the DSL, train a few steps, and
verify cost decreases — the shape of the reference's
``test_TrainerOnePass.cpp`` assertions."""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.config import dsl
from paddle_tpu.data import DataFeeder, dense_vector, integer_value
from paddle_tpu.optim import Momentum, Adam
from paddle_tpu.trainer import SGD


def _toy_classification(n=256, dim=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim, classes)
    x = rng.randn(n, dim).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.randn(n, classes), axis=1)
    return x, y.astype(np.int64)


def _batches(x, y, bs):
    def reader():
        for i in range(0, len(x), bs):
            yield [(x[j], int(y[j])) for j in range(i, min(i + bs, len(x)))]
    return reader


def test_mlp_trains():
    dsl.reset()
    img = dsl.data(name="x", size=8)
    lab = dsl.data(name="label", size=4)
    h = dsl.fc(input=img, size=32, act="relu")
    out = dsl.fc(input=h, size=4, act="softmax")
    cost = dsl.classification_cost(input=out, label=lab)

    trainer = SGD(cost=cost, update_equation=Momentum(
        learning_rate=0.1, momentum=0.9))
    x, y = _toy_classification()
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})

    costs = []
    trainer.train(_batches(x, y, 64), feeder=feeder, num_passes=8,
                  event_handler=lambda e: costs.append(e.cost)
                  if hasattr(e, "cost") else None)
    assert costs[0] > costs[-1], (costs[0], costs[-1])
    assert costs[-1] < 0.7 * costs[0]

    res = trainer.test(_batches(x, y, 64), feeder=feeder)
    assert res.evaluator["classification_error"] < 0.25


def test_regression_mse():
    dsl.reset()
    x_l = dsl.data(name="x", size=4)
    y_l = dsl.data(name="y", size=1)
    pred = dsl.fc(input=x_l, size=1, act="linear")
    cost = dsl.square_error_cost(input=pred, label=y_l)

    rng = np.random.RandomState(1)
    w = rng.randn(4, 1)
    x = rng.randn(512, 4).astype(np.float32)
    y = (x @ w).astype(np.float32)

    def reader():
        for i in range(0, len(x), 128):
            yield [(x[j], y[j]) for j in range(i, min(i + 128, len(x)))]

    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=0.05))
    feeder = DataFeeder({"x": dense_vector(4), "y": dense_vector(1)})
    costs = []
    trainer.train(reader, feeder=feeder, num_passes=20,
                  event_handler=lambda e: costs.append(e.cost)
                  if hasattr(e, "cost") else None)
    assert costs[-1] < 0.05 * costs[0]


def test_lstm_sequence_classification():
    dsl.reset()
    # variable-length sequences of token ids; class = parity of max token
    vocab, emb, hidden, classes = 20, 16, 32, 2
    words = dsl.data(name="words", size=vocab, is_sequence=True)
    lab = dsl.data(name="label", size=classes)
    e = dsl.embedding(input=words, size=emb, vocab_size=vocab)
    proj = dsl.fc(input=e, size=hidden * 4, act="linear")
    lstm = dsl.lstmemory(input=proj)
    pooled = dsl.pooling(input=lstm, pooling_type="max")
    out = dsl.fc(input=pooled, size=classes, act="softmax")
    cost = dsl.classification_cost(input=out, label=lab)

    rng = np.random.RandomState(2)
    data = []
    for _ in range(256):
        L = rng.randint(3, 12)
        seq = rng.randint(0, vocab, size=L)
        data.append((list(seq), int(seq.max() % 2)))

    from paddle_tpu.data import integer_value_sequence
    feeder = DataFeeder({"words": integer_value_sequence(vocab),
                         "label": integer_value(classes)}, pad_multiple=16)

    def reader():
        for i in range(0, len(data), 64):
            yield data[i:i + 64]

    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=0.01))
    costs = []
    trainer.train(reader, feeder=feeder, num_passes=12,
                  event_handler=lambda e: costs.append(e.cost)
                  if hasattr(e, "cost") else None)
    assert costs[-1] < 0.8 * costs[0], (costs[0], costs[-1])


def test_bf16_compute_keeps_masks_f32():
    """Mixed precision must NOT cast sequence masks: they are count data
    (token sums, per-row lengths) and bf16 saturates at 256 — a batch
    with >256 live tokens would report garbage error denominators."""
    import jax.numpy as jnp

    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.optim import Momentum
    from paddle_tpu.trainer import SGD

    dsl.reset()
    x = dsl.data(name="x", size=4, is_sequence=True)
    lab = dsl.data(name="label", size=2)
    pooled = dsl.pooling(input=dsl.fc(input=x, size=8), pooling_type="avg")
    out = dsl.fc(input=pooled, size=2, act="softmax")
    cost = dsl.classification_cost(input=out, label=lab)
    tr = SGD(cost=cost, update_equation=Momentum(learning_rate=0.1),
             compute_dtype="bfloat16")

    # 2 x 300 = 600 live tokens: far past bf16's 256 integer ceiling
    feed = {
        "x": Argument(value=jnp.ones((2, 300, 4), jnp.float32),
                      mask=jnp.ones((2, 300), jnp.float32)),
        "label": Argument(value=jnp.zeros((2,), jnp.int32)),
    }
    cast = tr._cast_compute(feed)
    assert cast["x"].value.dtype == jnp.bfloat16
    assert cast["x"].mask.dtype == jnp.float32  # counts stay exact
    assert float(jnp.sum(cast["x"].mask)) == 600.0


def test_param_attr_without_init_keeps_const_init():
    """An explicit ParamAttr carrying only non-init knobs (learning_rate)
    must not clobber a layer's deliberate const init — batch-norm gamma
    stays 1.0 (the reference's BN gamma default)."""
    import numpy as np

    import jax

    from paddle_tpu.compat import parse_config_and_serialize  # noqa: F401
    from paddle_tpu.compat.config_parser import begin_parse
    from paddle_tpu.compat.trainer_config_helpers import (batch_norm_layer,
                                                          data_layer)
    from paddle_tpu.compat.trainer_config_helpers.attrs import (
        ParameterAttribute)
    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network

    begin_parse()
    din = data_layer(name="input", size=8)
    bn = batch_norm_layer(input=din, name="bn",
                          param_attr=ParameterAttribute(learning_rate=0.1))
    net = Network(dsl.current_graph(), outputs=[bn.name])
    params = net.init_params(jax.random.PRNGKey(0))
    gamma = np.asarray(params["_bn.w0"])
    np.testing.assert_allclose(gamma, 1.0)  # const init survives
    # and the lr override itself took effect
    assert net.param_specs["_bn.w0"].learning_rate == 0.1
