"""Beam-search generation tests — the analogue of the reference's
``test_recurrent_machine_generation.cpp`` (greedy vs beam consistency,
golden sequences)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument
from paddle_tpu.core.generation import SequenceGenerator
from paddle_tpu.core.network import Network

V, E, H = 6, 4, 5
EOS = 1


def _build_gen_model(beam_size=3, max_length=8):
    """Tiny LM: h_t = tanh(W [emb;h]); p = softmax(U h). Deterministic
    weights so generation is reproducible."""
    dsl.reset()
    # an outer "encoder": context vector boots the memory
    src = dsl.data("src", size=H)
    boot = dsl.fc(src, size=H, act="tanh", name="boot", bias_attr=False)

    def step(prev_emb):
        m = dsl.memory(name="h", size=H, boot_layer=boot)
        h = dsl.fc([prev_emb, m], size=H, act="tanh", name="h",
                   bias_attr=False)
        p = dsl.fc(h, size=V, act="softmax", name="prob", bias_attr=False)
        return p

    out = dsl.beam_search(
        step,
        [dsl.GeneratedInput(size=V, embedding_name="gen_emb",
                            embedding_size=E)],
        bos_id=0, eos_id=EOS, beam_size=beam_size, max_length=max_length,
        name="gen")
    graph = dsl.current_graph()
    return graph, out


def _params(graph, out, seed=0):
    net = Network(graph, outputs=["boot"])
    params = dict(net.init_params(jax.random.PRNGKey(seed)))
    # beam group params are hoisted; add them + the shared embedding
    from paddle_tpu.core.registry import get_layer_impl
    cfg = graph.layers["gen"]
    impl = get_layer_impl("beam_search_group")
    rng = np.random.RandomState(seed)
    for suffix, spec in impl.params(cfg, []).items():
        name = spec.absolute_name
        params[name] = jnp.asarray(
            rng.randn(*spec.shape).astype(np.float32) * 0.7)
    params["gen_emb"] = jnp.asarray(
        rng.randn(V, E).astype(np.float32))
    return net, params


def test_greedy_matches_manual_unroll():
    graph, out = _build_gen_model()
    net, params = _params(graph, out)
    B = 2
    srcv = np.random.RandomState(7).randn(B, H).astype(np.float32)
    outer = net.apply(params, {"src": Argument(value=jnp.asarray(srcv))})
    gen = SequenceGenerator(graph, "gen")
    tokens, scores, lengths = gen.generate(params, outer, beam_size=1,
                                           max_length=8)
    tokens = np.asarray(tokens)

    # manual greedy unroll in numpy
    emb = np.asarray(params["gen_emb"])
    Wh = np.asarray(params["_h.w0"])   # [E, H]
    Wm = np.asarray(params["_h.w1"])   # [H, H]
    U = np.asarray(params["_prob.w0"])  # [H, V]
    h = np.asarray(outer["boot"].value)
    prev = np.zeros(B, np.int64)  # bos
    done = np.zeros(B, bool)
    for t in range(8):
        hn = np.tanh(emb[prev] @ Wh + h @ Wm)
        logits = hn @ U
        nxt = np.argmax(logits, axis=-1)
        for b in range(B):
            if not done[b]:
                assert tokens[b, 0, t] == nxt[b], (b, t)
        h = hn
        prev = nxt
        done |= nxt == EOS
        if done.all():
            break


def test_beam_search_top_beam_at_least_greedy():
    graph, out = _build_gen_model()
    net, params = _params(graph, out, seed=3)
    B = 3
    srcv = np.random.RandomState(11).randn(B, H).astype(np.float32)
    outer = net.apply(params, {"src": Argument(value=jnp.asarray(srcv))})
    gen = SequenceGenerator(graph, "gen")
    t1, s1, l1 = gen.generate(params, outer, beam_size=1, max_length=6)
    t4, s4, l4 = gen.generate(params, outer, beam_size=4, max_length=6)
    s1, s4 = np.asarray(s1), np.asarray(s4)
    # beam search can only improve on greedy
    assert (s4[:, 0] >= s1[:, 0] - 1e-5).all()
    # beams come back sorted best-first
    assert (np.diff(s4, axis=1) <= 1e-6).all()
    # all beams are distinct token sequences
    t4 = np.asarray(t4)
    for b in range(B):
        seqs = {tuple(t4[b, k]) for k in range(4)}
        assert len(seqs) == 4


def test_eos_terminates_and_lengths():
    graph, out = _build_gen_model()
    net, params = _params(graph, out, seed=5)
    # force EOS to dominate: bias the prob layer toward EOS via the
    # embedding column trick — instead just check length bookkeeping
    B = 2
    srcv = np.zeros((B, H), np.float32)
    outer = net.apply(params, {"src": Argument(value=jnp.asarray(srcv))})
    gen = SequenceGenerator(graph, "gen")
    tokens, scores, lengths = gen.generate(params, outer, beam_size=2,
                                           max_length=5)
    tokens, lengths = np.asarray(tokens), np.asarray(lengths)
    for b in range(B):
        for k in range(2):
            L = lengths[b, k]
            if L < 5:
                assert tokens[b, k, L - 1] == EOS
                # everything after first EOS stays EOS (frozen beams)
                assert (tokens[b, k, L - 1:] == EOS).all()
