"""Pipeline parallelism: the GPipe schedule over the pipe axis equals
sequential stage application, gradients flow to every stage's params,
and the program carries the collective-permute."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel.pipeline import (make_pipeline, sequential_apply,
                                          shard_pipeline_params,
                                          stack_stage_params)

D, B, S, M = 8, 16, 4, 4


def stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _pipe_mesh():
    devs = np.asarray(jax.devices()[:S]).reshape(S)
    return Mesh(devs, ("pipe",))


@pytest.fixture()
def setup():
    keys = jax.random.split(jax.random.PRNGKey(0), S)
    stages = [{"w": jax.random.normal(k, (D, D)) * 0.5,
               "b": jnp.zeros(D)} for k in keys]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))
    return stacked, x


def test_pipeline_matches_sequential(setup):
    stacked, x = setup
    ref = sequential_apply(stage_fn, stacked, x)
    mesh = _pipe_mesh()
    fn = make_pipeline(mesh, "pipe", stage_fn, n_microbatches=M)
    got = fn(shard_pipeline_params(stacked, mesh, "pipe"), x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=2e-5, atol=2e-6)


def test_pipeline_single_microbatch_also_correct(setup):
    stacked, x = setup
    ref = sequential_apply(stage_fn, stacked, x)
    mesh = _pipe_mesh()
    fn = make_pipeline(mesh, "pipe", stage_fn, n_microbatches=1)
    got = fn(shard_pipeline_params(stacked, mesh, "pipe"), x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=2e-5, atol=2e-6)


def test_pipeline_grads_reach_every_stage(setup):
    stacked, x = setup
    mesh = _pipe_mesh()
    fn = make_pipeline(mesh, "pipe", stage_fn, n_microbatches=M)
    sharded = shard_pipeline_params(stacked, mesh, "pipe")
    y_t = jax.random.normal(jax.random.PRNGKey(2), (B, D))

    def loss(p):
        return jnp.mean((fn(p, x) - y_t) ** 2)

    grads = jax.grad(loss)(sharded)
    gw = np.asarray(grads["w"])
    for s in range(S):
        assert np.abs(gw[s]).sum() > 0, f"stage {s} got no gradient"

    # and the sharded grads match the sequential formulation's grads
    def ref_loss(p):
        return jnp.mean((sequential_apply(stage_fn, p, x) - y_t) ** 2)

    ref_grads = jax.grad(ref_loss)(stacked)
    np.testing.assert_allclose(gw, np.asarray(ref_grads["w"]),
                               rtol=2e-4, atol=1e-6)


def test_pipeline_program_has_collective_permute(setup):
    stacked, x = setup
    mesh = _pipe_mesh()
    fn = make_pipeline(mesh, "pipe", stage_fn, n_microbatches=M)
    hlo = jax.jit(fn).lower(
        shard_pipeline_params(stacked, mesh, "pipe"), x).compile().as_text()
    assert "collective-permute" in hlo


# ----------------------------------------------- device-attr config path
def test_pipeline_from_device_attrs_matches_sequential():
    """The reference's per-layer `device` placement spelling maps to
    GPipe stages (VERDICT r04 weak #5: PP must be config-reachable):
    a config of 4 identical fc blocks pinned device=0..3 pipelines over
    a 4-way pipe mesh and matches the unpipelined forward."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.core.network import Network
    from paddle_tpu.parallel.pipeline import (
        make_pipeline_from_device_attrs, sequential_apply,
        stages_from_device_attrs)

    dsl.reset()
    x = dsl.data(name="x", size=16)
    h = x
    for s in range(4):
        h = dsl.fc(input=h, size=16, act="tanh", name=f"blk{s}",
                   layer_attr={"device": s})
    g = dsl.current_graph()
    assert stages_from_device_attrs(g) == [["blk0"], ["blk1"],
                                           ["blk2"], ["blk3"]]
    net = Network(g, outputs=["blk3"])
    params = net.init_params(jax.random.PRNGKey(0))
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))
    fn, stacked = make_pipeline_from_device_attrs(
        g, params, mesh, "pipe", n_microbatches=4, full_net=net)
    X = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
    got = fn(stacked, X)
    want = net.apply(params, {"x": Argument(value=X)},
                     train=False)["blk3"].value
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # and the sequential reference path agrees too
    seq = sequential_apply(fn.stage_fn,
                           {k: np.asarray(jax.device_get(v))
                            for k, v in stacked.items()}, X)
    np.testing.assert_allclose(np.asarray(seq), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_from_device_attrs_rejects_bad_configs():
    import pytest as _pytest

    from paddle_tpu.config import dsl
    from paddle_tpu.parallel.pipeline import stages_from_device_attrs

    dsl.reset()
    x = dsl.data(name="x", size=8)
    h = dsl.fc(input=x, size=8, name="a", layer_attr={"device": 0})
    dsl.fc(input=h, size=8, name="b")  # no device attr
    with _pytest.raises(ValueError, match="no device attr"):
        stages_from_device_attrs(dsl.current_graph())

    dsl.reset()
    x = dsl.data(name="x", size=8)
    h = dsl.fc(input=x, size=8, name="a", layer_attr={"device": 0})
    dsl.fc(input=h, size=8, name="b", layer_attr={"device": 2})
    with _pytest.raises(ValueError, match="contiguous"):
        stages_from_device_attrs(dsl.current_graph())


def _two_stage_graph(stage1_wiring="chain"):
    """Two structurally identical 2-fc stages; stage 1 optionally breaks
    the chain contract in a way the (type, size) signature can't see."""
    from paddle_tpu.config import dsl

    dsl.reset()
    x = dsl.data(name="x", size=8)
    a0 = dsl.fc(input=x, size=8, name="a0", layer_attr={"device": 0})
    b0 = dsl.fc(input=a0, size=8, name="b0", layer_attr={"device": 0})
    if stage1_wiring == "chain":
        a1 = dsl.fc(input=b0, size=8, name="a1", layer_attr={"device": 1})
        dsl.fc(input=a1, size=8, name="b1", layer_attr={"device": 1})
    elif stage1_wiring == "fan_in":
        a1 = dsl.fc(input=b0, size=8, name="a1", layer_attr={"device": 1})
        # 2-input fc: same (type, size) signature, different topology
        dsl.fc(input=[a1, a0], size=8, name="b1",
               layer_attr={"device": 1})
    else:  # skip: consumes a non-predecessor
        a1 = dsl.fc(input=a0, size=8, name="a1", layer_attr={"device": 1})
        dsl.fc(input=a1, size=8, name="b1", layer_attr={"device": 1})
    return dsl.current_graph()


def test_pipeline_validates_fan_in_for_every_stage():
    """ADVICE r05 #2: a later stage with the stage-0 (type, size)
    signature but different fan-in/topology must be REJECTED, not
    silently executed with stage-0's wiring."""
    import numpy as np

    import jax
    import pytest as _pytest
    from jax.sharding import Mesh

    from paddle_tpu.core.network import Network
    from paddle_tpu.parallel.pipeline import make_pipeline_from_device_attrs

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("pipe",))

    def build(wiring):
        g = _two_stage_graph(wiring)
        net = Network(g, outputs=["b1"])
        params = net.init_params(jax.random.PRNGKey(0))
        return make_pipeline_from_device_attrs(
            g, params, mesh, "pipe", n_microbatches=2, full_net=net)

    build("chain")  # the valid spelling still builds
    with _pytest.raises(ValueError, match="single"):
        build("fan_in")
    with _pytest.raises(ValueError, match="predecessor"):
        build("skip")
