"""Raw-API sequence generation: ``GradientMachine.asSequenceGenerator``
→ ``generateSequence`` → ``ISequenceResults`` (``PaddleAPI.h:1024-1046``,
``api/SequenceGenerator.cpp``), the SWIG generation surface the reference
exposes as ``paddle_gen_sequence``. The N-best output must match the
engine's own jitted beam search (``core/generation.py``) — the SWIG layer
is a shim, not a second implementation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.compat import swig_api as api
from paddle_tpu.config import dsl
from paddle_tpu.core.argument import Argument


def _generating_machine(seed=5):
    """Deterministic generating seq2seq machine (mirrors
    test_seq_models._gen_setup so the goldens line up)."""
    from paddle_tpu.models import seq2seq_attention
    dsl.reset()
    seq2seq_attention(src_vocab=20, trg_vocab=12, embed_dim=8,
                      hidden=8, beam_size=3, max_length=8,
                      generating=True)
    graph = dsl.current_graph()
    m = api.GradientMachine.createFromConfigProto(graph)
    rng = np.random.RandomState(seed)
    for name in sorted(m._params):
        spec = m._meta[name]
        m._params[name] = jnp.asarray(
            rng.randn(*spec.shape).astype(np.float32) * 0.5)
    emb_name = "_trg_emb.w0"
    if emb_name not in m._params:
        m._params[emb_name] = jnp.asarray(
            rng.randn(12, 8).astype(np.float32) * 0.5)
    return m, graph


def _engine_nbest(graph, params, src, K=3, L=8):
    """The engine's own answer for the same inputs."""
    from paddle_tpu.core.generation import SequenceGenerator
    from paddle_tpu.core.network import Network
    gen_name = next(n for n, l in graph.layers.items()
                    if l.type == "beam_search_group")
    sg = SequenceGenerator(graph, gen_name)
    net = Network(graph, outputs=sg.static_input_layers())
    feed = {"source_words": Argument(
        value=jnp.asarray(src),
        mask=jnp.ones(src.shape, jnp.float32))}
    outer = net.apply(params, feed, train=False)
    return sg.generate(params, outer, beam_size=K, max_length=L)


def _src_args(src):
    """source ids as one flat sequence Arguments (the raw-API layout:
    flat ids + sequenceStartPositions offsets)."""
    args = api.Arguments.createArguments(1)
    flat = src.reshape(-1).astype(np.int32)
    B, T = src.shape
    starts = np.arange(0, (B + 1) * T, T, dtype=np.int32)
    args.setSlotIds(0, api.IVector.createVectorFromNumpy(flat))
    args.setSlotSequenceStartPositions(
        0, api.IVector.createVectorFromNumpy(starts))
    return args


def test_generate_matches_engine_beams():
    m, graph = _generating_machine()
    gen = m.asSequenceGenerator(dict=[f"w{i}" for i in range(12)],
                                max_length=8, beam_size=3)
    src = np.array([[2, 5, 7, 9], [3, 4, 6, 8]], np.int32)
    res = gen.generateSequence(_src_args(src))
    tokens, scores, lengths = _engine_nbest(graph, m._params, src)
    tokens, scores, lengths = (np.asarray(tokens), np.asarray(scores),
                               np.asarray(lengths))
    B, K = tokens.shape[0], tokens.shape[1]
    assert res.getSize() == B * K
    for b in range(B):
        for k in range(K):
            i = b * K + k
            want = tokens[b, k, : int(lengths[b, k])].tolist()
            assert res.getSequence(i) == want, (b, k)
            assert res.getScore(i) == pytest.approx(
                float(scores[b, k]), rel=1e-5)
    # beams sorted best-first within each sequence (the reference's
    # partial_sort contract)
    for b in range(B):
        ss = [res.getScore(b * K + k) for k in range(K)]
        assert all(ss[j] >= ss[j + 1] - 1e-6 for j in range(K - 1))


def test_sentence_rendering_and_range_errors():
    m, _ = _generating_machine()
    words = [f"w{i}" for i in range(12)]
    gen = m.asSequenceGenerator(max_length=6, beam_size=2)
    gen.setDict(words)
    src = np.array([[2, 5, 7, 9]], np.int32)
    res = gen.generateSequence(_src_args(src))
    ids = res.getSequence(0)
    assert res.getSentence(0, True) == " ".join(words[i] for i in ids)
    assert res.getSentence(0) == "".join(words[i] for i in ids)
    with pytest.raises(api.RangeError):
        res.getSequence(res.getSize())
    with pytest.raises(api.RangeError):
        res.getScore(-1)


def test_setters_control_search():
    m, graph = _generating_machine()
    gen = m.asSequenceGenerator()
    gen.setBeamSize(2)
    gen.setMaxLength(5)
    src = np.array([[2, 5, 7, 9]], np.int32)
    res = gen.generateSequence(_src_args(src))
    assert res.getSize() == 2          # K from setBeamSize
    assert all(len(res.getSequence(i)) <= 5 for i in range(2))
    # bos/eos overrides re-trace the search: forcing eos to a different
    # token changes where sequences may terminate
    cfg_eos = graph.layers[next(
        n for n, l in graph.layers.items()
        if l.type == "beam_search_group")].attrs["gen"]["eos_id"]
    gen.setEos((cfg_eos + 1) % 12)
    res2 = gen.generateSequence(_src_args(src))
    assert res2.getSize() == 2
    seqs = {tuple(res.getSequence(i)) for i in range(2)}
    seqs2 = {tuple(res2.getSequence(i)) for i in range(2)}
    assert seqs != seqs2


def test_generate_without_generating_config_raises():
    dsl.reset()
    x = dsl.data(name="x", size=4)
    out = dsl.fc(input=x, size=2, act="softmax")
    dsl.classification_cost(input=out, label=dsl.data(name="l", size=2))
    m = api.GradientMachine.createFromConfigProto(dsl.current_graph())
    with pytest.raises(api.UnsupportError):
        m.asSequenceGenerator().generateSequence(
            api.Arguments.createArguments(0))
