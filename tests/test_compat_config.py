"""v1 config-compat pipeline tests.

The north-star contract: reference v1 configs (`python/paddle/
trainer_config_helpers/tests/configs/*.py`, `v1_api_demo/*/*.py`) parse
through ``paddle_tpu.compat.parse_config`` unmodified, export the wire
protos (``TrainerConfigHelper.cpp:33-57`` contract), and train through the
CLI. Structural parity is checked against the reference's golden protostr
files (``tests/configs/protostr/*.protostr``), the same goldens its
``ProtobufEqualMain.cpp`` harness compares.
"""

import os
import pathlib
import textwrap

import pytest

from paddle_tpu.compat import parse_config

REF = pathlib.Path("/root/reference")
CFG_DIR = REF / "python/paddle/trainer_config_helpers/tests/configs"
GOLDEN_DIR = CFG_DIR / "protostr"

needs_ref = pytest.mark.skipif(not REF.exists(), reason="needs reference")

# Every config in the reference's own test list (`tests/configs/
# file_list.sh` — 42 configs + test_split_datasource) parses. test_crop.py
# is excluded there too: it is broken at the source (duplicate layer name
# 'data', and `outputs(pad)` references the helper function).
PARSING_CONFIGS = [
    "img_layers.py", "img_trans_layers.py", "last_first_seq.py",
    "layer_activations.py", "math_ops.py", "projections.py",
    "shared_fc.py", "shared_gru.py", "shared_lstm.py",
    "simple_rnn_layers.py", "test_bi_grumemory.py",
    "test_bilinear_interp.py", "test_clip_layer.py",
    "test_config_parser_for_non_file_config.py", "test_cost_layers.py",
    "test_cost_layers_with_weight.py",
    "test_detection_output_layer.py", "test_expand_layer.py", "test_fc.py",
    "test_gated_unit_layer.py", "test_grumemory_layer.py",
    "test_hsigmoid.py", "test_kmax_seq_socre_layer.py",
    "test_lstmemory_layer.py", "test_maxout.py",
    "test_multibox_loss_layer.py", "test_multiplex_layer.py",
    "test_ntm_layers.py", "test_pad.py", "test_prelu_layer.py",
    "test_print_layer.py", "test_recursive_topology.py",
    "test_repeat_layer.py", "test_rnn_group.py", "test_row_conv.py",
    "test_row_l2_norm_layer.py", "test_seq_concat_reshape.py",
    "test_seq_select_layers.py", "test_sequence_pooling.py",
    "test_smooth_l1.py", "test_split_datasource.py", "test_spp_layer.py",
    "unused_layers.py", "util_layers.py",
]

# configs whose golden protostr our export matches structurally (layer
# names/types/sizes/wiring + parameter names/dims): EVERY config in the
# reference's list that ships a golden — including the recurrent-group
# expansions (scoped step layers, scatter/gather agents, +delay memories)
GOLDEN_PARITY_CONFIGS = [
    n for n in PARSING_CONFIGS
    if (GOLDEN_DIR / (n[:-3] + ".protostr")).exists()
]


def test_install_paddle_alias_importable():
    """ADVICE r2 (high): the advertised entry point must actually import."""
    from paddle_tpu.compat import install_paddle_alias
    root = install_paddle_alias()
    import importlib
    import sys
    assert sys.modules["paddle"] is root
    tch = importlib.import_module("paddle.trainer_config_helpers")
    for name in ("data_layer", "fc_layer", "settings", "get_config_arg",
                 "inputs", "outputs", "define_py_data_sources2",
                 "small_vgg", "L1Regularization", "MomentumOptimizer"):
        assert hasattr(tch, name), name
    pdp2 = importlib.import_module("paddle.trainer.PyDataProvider2")
    assert hasattr(pdp2, "provider")


@needs_ref
@pytest.mark.parametrize("name", PARSING_CONFIGS)
def test_reference_golden_config_parses(name):
    parsed = parse_config(str(CFG_DIR / name))
    mp = parsed.model_proto()
    # group expansion emits extra agent/shell layers beyond the DSL graph
    assert len(mp.layers) >= len(parsed.model.layers)
    # serialized bytes parse back under the schema
    blob = mp.SerializeToString()
    from paddle_tpu.proto import ModelConfig_pb2
    rt = ModelConfig_pb2.ModelConfig.FromString(blob)
    assert [l.name for l in rt.layers] == [l.name for l in mp.layers]


def _golden_model(name):
    from google.protobuf import text_format
    from paddle_tpu.proto import ModelConfig_pb2, TrainerConfig_pb2
    txt = (GOLDEN_DIR / (name[:-3] + ".protostr")).read_text()
    mc = ModelConfig_pb2.ModelConfig()
    try:
        text_format.Parse(txt, mc)
        return mc
    except text_format.ParseError:
        tc = TrainerConfig_pb2.TrainerConfig()
        text_format.Parse(txt, tc)
        return tc.model_config


# ---- full-field parity normalizations -------------------------------------
# The short, documented list of wire-format divergences between our
# exporter and the reference goldens. Everything NOT cleared here is
# compared verbatim by test_golden_protostr_full_field_parity.
def normalize_layer_pair(ours, gold):
    pass


def normalize_param_pair(ours, gold):
    """Zero entries (VERDICT r04 item #6): parameters are compared
    VERBATIM — the wire carries the reference's exact dims (3-dim
    fused-gate blocks for lstm/tensor, dimless conv/batch-norm-scale
    params via ``ParamSpec.wire_dims``); the engine reshapes at its own
    boundary."""
    pass


@needs_ref
@pytest.mark.parametrize("name", GOLDEN_PARITY_CONFIGS)
def test_golden_protostr_full_field_parity(name):
    """Complete LayerConfig/ParameterConfig text-format equality against
    the reference goldens, modulo the explicit normalize_* whitelist —
    the ``ProtobufEqualMain.cpp`` bar (the structural test above checks
    the load-bearing subset and predates this)."""
    from google.protobuf import text_format
    parsed = parse_config(str(CFG_DIR / name))
    ours = parsed.model_proto()
    ref = _golden_model(name)
    assert [l.name for l in ours.layers] == [l.name for l in ref.layers]
    for ol, rl in zip(ours.layers, ref.layers):
        normalize_layer_pair(ol, rl)
        assert text_format.MessageToString(ol) == \
            text_format.MessageToString(rl), ol.name
    ours_p = {p.name: p for p in ours.parameters}
    ref_p = {p.name: p for p in ref.parameters}
    assert set(ours_p) == set(ref_p)
    for pname in sorted(ours_p):
        a, b = ours_p[pname], ref_p[pname]
        assert a.size == b.size, pname
        normalize_param_pair(a, b)
        assert text_format.MessageToString(a) == \
            text_format.MessageToString(b), pname
    # ... and the REST of the proto verbatim: sub_models (incl. the
    # recurrent expansions' in/out links and memories), declared
    # input/output orders, and evaluator configs
    for msg in (ours, ref):
        del msg.layers[:]
        del msg.parameters[:]
    assert text_format.MessageToString(ours) == \
        text_format.MessageToString(ref)


@needs_ref
@pytest.mark.parametrize("name", GOLDEN_PARITY_CONFIGS)
def test_golden_protostr_structural_parity(name):
    """Layer names, types, sizes, input wiring, and parameter names/dims
    must match the reference's golden protos exactly."""
    parsed = parse_config(str(CFG_DIR / name))
    ours = parsed.model_proto()
    ref = _golden_model(name)
    assert [l.name for l in ours.layers] == [l.name for l in ref.layers]
    for ol, rl in zip(ours.layers, ref.layers):
        assert ol.type == rl.type, ol.name
        assert ol.size == rl.size, ol.name
        assert ol.active_type == rl.active_type, ol.name
        assert [i.input_layer_name for i in ol.inputs] == \
            [i.input_layer_name for i in rl.inputs], ol.name
        assert [i.input_parameter_name for i in ol.inputs] == \
            [i.input_parameter_name for i in rl.inputs], ol.name
        assert ol.bias_parameter_name == rl.bias_parameter_name, ol.name
    # parameter names and total sizes must match; dim *layouts* may differ
    # (e.g. our lstm packs w0 as (H, 4H) where the reference uses (H, H, 4))
    ours_params = {p.name: p.size for p in ours.parameters}
    ref_params = {p.name: p.size for p in ref.parameters}
    assert ours_params == ref_params
    assert list(ours.input_layer_names) == list(ref.input_layer_names)
    assert list(ours.output_layer_names) == list(ref.output_layer_names)
    assert [(e.type, e.name, list(e.input_layers)) for e in
            ours.evaluators] == \
        [(e.type, e.name, list(e.input_layers)) for e in ref.evaluators]


@needs_ref
def test_vgg16_mnist_reference_config():
    """`v1_api_demo/mnist/vgg_16_mnist.py` — the north-star demo config —
    parses unmodified, in both train and predict modes."""
    cfg = str(REF / "v1_api_demo/mnist/vgg_16_mnist.py")
    parsed = parse_config(cfg)
    assert parsed.context.train_source.module == "mnist_provider"
    assert parsed.context.settings["batch_size"] == 128
    costs = parsed.cost_layers()
    assert len(costs) == 1
    tp = parsed.trainer_proto()
    assert tp.opt_config.learning_method == "momentum"
    assert tp.data_config.load_data_module == "mnist_provider"
    assert len(tp.model_config.layers) > 20  # the full VGG stack
    opt = parsed.optimizer()
    assert type(opt).__name__ == "Momentum"

    pred = parse_config(cfg, "is_predict=1")
    assert not pred.cost_layers()


@needs_ref
def test_rnn_crf_reference_config_parses():
    """The sequence-tagging north-star config
    (`v1_api_demo/sequence_tagging/rnn_crf.py`) parses unmodified."""
    parsed = parse_config(str(REF / "v1_api_demo/sequence_tagging/rnn_crf.py"))
    assert parsed.cost_layers() == ["__crf_layer_0__"]
    mp = parsed.model_proto()
    types = {l.type for l in mp.layers}
    # embedding layers export as mixed+table (the reference's wire form)
    assert {"crf", "recurrent", "mixed"} <= types


@needs_ref
@pytest.mark.parametrize("path,min_layers", [
    ("v1_api_demo/gan/gan_conf.py", 5),
    ("v1_api_demo/gan/gan_conf_image.py", 8),
    ("v1_api_demo/vae/vae_conf.py", 20),
    ("v1_api_demo/traffic_prediction/trainer_config.py", 90),
    ("v1_api_demo/model_zoo/resnet/resnet.py", 120),
    ("v1_api_demo/sequence_tagging/linear_crf.py", 7),
])
def test_v1_demo_config_parses(path, min_layers):
    """The remaining v1_api_demo configs — GAN (incl. conv-transpose image
    GAN), VAE (layer_math arithmetic), traffic prediction, the model-zoo
    ResNet, linear-CRF tagging — parse unmodified."""
    parsed = parse_config(str(REF / path))
    assert len(parsed.model.layers) >= min_layers
    assert parsed.model_proto().layers


@needs_ref
def test_parse_config_and_serialize_reference_schema_roundtrip(tmp_path):
    """Serialized TrainerConfig bytes parse under the *reference's* compiled
    schema — the C++ consumer contract."""
    import shutil
    import subprocess
    if shutil.which("protoc") is None:
        pytest.skip("needs protoc")
    from google.protobuf import (descriptor_pb2, descriptor_pool,
                                 message_factory)
    from paddle_tpu.compat import parse_config_and_serialize
    blob = parse_config_and_serialize(str(CFG_DIR / "test_fc.py"))

    out = tmp_path / "ref.desc"
    subprocess.run(
        ["protoc", f"-I{REF / 'proto'}", "-o", str(out),
         "--include_imports", "TrainerConfig.proto"],
        check=True, cwd=REF / "proto")
    fds = descriptor_pb2.FileDescriptorSet.FromString(out.read_bytes())
    pool = descriptor_pool.DescriptorPool()
    for f in fds.file:
        pool.Add(f)
    ref_cls = message_factory.GetMessageClass(
        pool.FindMessageTypeByName("paddle.TrainerConfig"))
    tc = ref_cls.FromString(blob)
    assert tc.opt_config.batch_size == 1000
    assert len(tc.model_config.layers) == 5


# --------------------------------------------------------- end-to-end train
V1_TRAIN_CONFIG = """\
from paddle.trainer_config_helpers import *

define_py_data_sources2(
    train_list='train.list', test_list='test.list',
    module='toy_provider', obj='process')

settings(
    batch_size=8,
    learning_rate=0.1,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(1e-4))

img = data_layer(name='pixel', size=16)
hidden = fc_layer(input=img, size=32, act=TanhActivation())
predict = fc_layer(input=hidden, size=4, act=SoftmaxActivation())
lbl = data_layer(name='label', size=4)
inputs(img, lbl)
outputs(classification_cost(input=predict, label=lbl))
"""

TOY_PROVIDER = """\
from paddle.trainer.PyDataProvider2 import *
import random


@provider(input_types={'pixel': dense_vector(16),
                       'label': integer_value(4)})
def process(settings, filename):
    rng = random.Random(42)
    for _ in range(64):
        label = rng.randrange(4)
        base = [0.0] * 16
        for i in range(4):
            base[label * 4 + i] = 1.0 + rng.random() * 0.1
        yield base, label
"""


@pytest.fixture
def v1_job_dir(tmp_path):
    (tmp_path / "trainer_config.py").write_text(V1_TRAIN_CONFIG)
    (tmp_path / "toy_provider.py").write_text(TOY_PROVIDER)
    (tmp_path / "data.txt").write_text("synthetic\n")
    (tmp_path / "train.list").write_text(str(tmp_path / "data.txt") + "\n")
    (tmp_path / "test.list").write_text(str(tmp_path / "data.txt") + "\n")
    return tmp_path


def test_cli_trains_v1_config(v1_job_dir, capsys):
    """`--config=<v1 config>` trains end-to-end through the compat
    compiler: the reference CLI contract (`TrainerMain.cpp:32-64`)."""
    from paddle_tpu.trainer import cli
    rc = cli.main(["--config", str(v1_job_dir / "trainer_config.py"),
                   "--job", "train", "--num_passes", "2",
                   "--log_period", "4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Pass 0" in out and "Pass 1" in out


def test_cli_tests_v1_config(v1_job_dir, capsys):
    from paddle_tpu.trainer import cli
    rc = cli.main(["--config", str(v1_job_dir / "trainer_config.py"),
                   "--job", "test"])
    assert rc == 0
    assert "Test: cost=" in capsys.readouterr().out


def test_v1_config_loss_decreases(v1_job_dir):
    """The compat pipeline doesn't just run — it learns: loss after two
    passes is below the first-batch loss."""
    from paddle_tpu.trainer import cli as cli_mod
    ns = cli_mod.load_config(str(v1_job_dir / "trainer_config.py"))
    from paddle_tpu.trainer.trainer import SGD
    trainer = SGD(cost=ns["cost"], update_equation=ns["optimizer"], seed=0)
    losses = []

    from paddle_tpu.trainer import events as ev

    def handler(e):
        if isinstance(e, ev.EndIteration):
            losses.append(float(e.cost))

    from paddle_tpu.data.feeder import DataFeeder
    feeder = DataFeeder(ns["feeding"])
    trainer.train(ns["train_reader"], feeder=feeder, num_passes=3,
                  event_handler=handler, log_period=1000)
    assert losses[-1] < losses[0] * 0.7


@needs_ref
@pytest.mark.parametrize("name", ["test_rnn_group.py", "shared_lstm.py",
                                  "shared_gru.py"])
def test_sub_models_match_golden(name):
    """The recurrent-group expansion's SubModelConfig blocks (scoped layer
    lists, in/out links, +delay memories, reversed flags) equal the
    reference's goldens."""
    parsed = parse_config(str(CFG_DIR / name))
    ours = parsed.model_proto()
    ref = _golden_model(name)
    assert len(ours.sub_models) == len(ref.sub_models)
    for o, r in zip(ours.sub_models, ref.sub_models):
        assert o.name == r.name
        assert list(o.layer_names) == list(r.layer_names), o.name
        assert o.is_recurrent_layer_group == r.is_recurrent_layer_group
        assert o.reversed == r.reversed, o.name
        assert [(m.layer_name, m.link_name, m.boot_layer_name)
                for m in o.memories] == \
            [(m.layer_name, m.link_name, m.boot_layer_name)
             for m in r.memories], o.name
        assert [(l.layer_name, l.link_name, l.has_subseq)
                for l in o.in_links] == \
            [(l.layer_name, l.link_name, l.has_subseq)
             for l in r.in_links], o.name
        assert [(l.layer_name, l.link_name) for l in o.out_links] == \
            [(l.layer_name, l.link_name) for l in r.out_links], o.name


@needs_ref
def test_reference_config_parser_test_invocations():
    """The reference's own parser unit test
    (`paddle/trainer/tests/config_parser_test.py`) — its three
    parse_config_and_serialize invocations succeed here, including the
    extension_module_name arg and the gserver pyDataProvider config."""
    import os
    from paddle_tpu.compat import install_paddle_alias
    install_paddle_alias()
    from paddle.trainer.config_parser import parse_config_and_serialize
    cwd = os.getcwd()
    os.chdir("/root/reference/paddle")
    try:
        for conf, arg in [
            ("trainer/tests/test_config.conf", ""),
            ("trainer/tests/sample_trainer_config.conf",
             "extension_module_name="
             "paddle.trainer.config_parser_extension"),
            ("gserver/tests/pyDataProvider/trainer.conf", ""),
        ]:
            blob = parse_config_and_serialize(conf, arg)
            assert isinstance(blob, bytes) and len(blob) > 500, conf
            from paddle_tpu.proto import TrainerConfig_pb2
            tc = TrainerConfig_pb2.TrainerConfig.FromString(blob)
            assert tc.model_config.layers
    finally:
        os.chdir(cwd)
