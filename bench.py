"""Benchmark harness. Prints ONE JSON line.

Round-1 metric: the reference's headline RNN benchmark — IMDB-style LSTM
text classification, batch 64, hidden 256, seqlen 100, dict 30k
(``/root/reference/benchmark/paddle/rnn/rnn.py``; published number
83 ms/batch on a K40m, ``benchmark/README.md:110-120``). We time the full
jitted train step (forward+backward+update, the same thing
``paddle_trainer --job=time`` measures) in steady state on one TPU chip.

vs_baseline = reference_ms / our_ms (>1 means faster than the reference).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

REFERENCE_MS = 83.0  # Paddle on K40m, benchmark/README.md:110-120
BATCH, HIDDEN, SEQLEN, VOCAB = 64, 256, 100, 30000
ITERS = int(os.environ.get("BENCH_ITERS", "30"))


def main():
    import jax
    from paddle_tpu.config import dsl
    from paddle_tpu.data import DataFeeder, integer_value, integer_value_sequence
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGD

    dsl.reset()
    cost, out, _ = lstm_text_classifier(
        vocab_size=VOCAB, embed_dim=128, hidden=HIDDEN, num_layers=2,
        classes=2)
    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=2e-3))

    rng = np.random.RandomState(0)
    feeder = DataFeeder({"words": integer_value_sequence(VOCAB),
                         "label": integer_value(2)}, pad_multiple=SEQLEN)
    batch = [(list(rng.randint(0, VOCAB, size=SEQLEN)), int(rng.randint(0, 2)))
             for _ in range(BATCH)]
    feed = feeder(batch)

    # warmup / compile
    rng_key = jax.random.PRNGKey(0)
    for _ in range(3):
        rng_key, step_key = jax.random.split(rng_key)
        trainer.params, trainer.opt_state, metrics = trainer._train_step(
            trainer.params, trainer.opt_state, feed, step_key, 0)
    jax.block_until_ready(metrics["cost"])

    iters = ITERS
    t0 = time.perf_counter()
    for _ in range(iters):
        rng_key, step_key = jax.random.split(rng_key)
        trainer.params, trainer.opt_state, metrics = trainer._train_step(
            trainer.params, trainer.opt_state, feed, step_key, 0)
    jax.block_until_ready(metrics["cost"])
    ms = (time.perf_counter() - t0) / iters * 1000.0

    print(json.dumps({
        "metric": "lstm_imdb_train_ms_per_batch_bs64_h256_seq100",
        "value": round(ms, 3),
        "unit": "ms/batch",
        "vs_baseline": round(REFERENCE_MS / ms, 3),
    }))


if __name__ == "__main__":
    main()
