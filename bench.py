"""Benchmark harness. Prints ONE JSON line — and cannot lose the result.

Two layers:

- **Orchestrator** (default): runs the measurement in a *subprocess* and
  retries with long backoff when the TPU backend fails to initialize (the
  tunnel drops intermittently; a fresh process is the only reliable way to
  re-attempt backend setup, since jax caches a failed backend). On total
  failure it still prints a JSON line carrying the error tail instead of a
  bare traceback.
- **Child** (``BENCH_CHILD=1``): the actual measurement.

Metrics:

- Primary: the reference's headline RNN benchmark — IMDB-style LSTM text
  classification, batch 64, hidden 256, seqlen 100, dict 30k
  (``/root/reference/benchmark/paddle/rnn/rnn.py``; published 83 ms/batch on
  a K40m, ``benchmark/README.md:110-120``). Full jitted train step
  (forward+backward+update), steady state, one chip — what
  ``paddle_trainer --job=time`` measures. vs_baseline = reference_ms / ours.
- Extras: ResNet-50 imgs/sec/chip + MFU (the BASELINE.json north-star
  metric; FLOPs from XLA's own cost analysis of the compiled step, peak
  from the device kind).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_MS = 83.0  # Paddle LSTM on K40m, benchmark/README.md:110-120
BATCH, HIDDEN, SEQLEN, VOCAB = 64, 256, 100, 30000
ITERS = int(os.environ.get("BENCH_ITERS", "100"))
RESNET_BATCH = int(os.environ.get("BENCH_RESNET_BATCH", "64"))
RESNET_ITERS = int(os.environ.get("BENCH_RESNET_ITERS", "30"))
RETRIES = int(os.environ.get("BENCH_RETRIES", "4"))
# short backoffs: the cheap probe already filters a wedged tunnel, so a
# failed attempt costs little and a recovering tunnel is caught quickly
BACKOFFS = [30, 60, 120]

# bf16 peak FLOP/s per chip by device kind (scaling-book numbers); used
# only for the MFU denominator. Unknown kinds fall back to v5e.
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}
DEFAULT_PEAK = 197e12


def _timed_chain(run_steps, fetch, n_long, n_short):
    """Steady-state seconds/step over a remote (tunneled) device.

    ``jax.block_until_ready`` does NOT wait through the axon tunnel — only a
    real device→host fetch does — so: chain n steps device-side, fetch one
    scalar, and take the difference quotient of a long and a short chain to
    cancel the constant round-trip latency."""

    def once(n):
        t0 = time.perf_counter()
        run_steps(n)
        fetch()
        return time.perf_counter() - t0

    n_short = min(n_short, n_long - 1)  # keep the quotient well-defined
    t_short = min(once(n_short) for _ in range(2)) if n_short else 0.0
    t_long = min(once(n_long) for _ in range(2))
    return max(t_long - t_short, 1e-9) / (n_long - n_short)


def bench_lstm(compute_dtype=None):
    import jax
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGD

    dsl.reset()
    cost, out, _ = lstm_text_classifier(
        vocab_size=VOCAB, embed_dim=128, hidden=HIDDEN, num_layers=2,
        classes=2)
    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=2e-3),
                  compute_dtype=compute_dtype)

    rng = np.random.RandomState(0)
    feeder = DataFeeder({"words": integer_value_sequence(VOCAB),
                         "label": integer_value(2)}, pad_multiple=SEQLEN)
    batch = [(list(rng.randint(0, VOCAB, size=SEQLEN)),
              int(rng.randint(0, 2))) for _ in range(BATCH)]
    feed = feeder(batch)

    rng_key = jax.random.PRNGKey(0)
    state = {"m": None}

    def run_steps(n):
        nonlocal rng_key
        for _ in range(n):
            rng_key, step_key = jax.random.split(rng_key)
            trainer.params, trainer.opt_state, metrics = trainer._train_step(
                trainer.params, trainer.opt_state, feed, step_key, 0)
            state["m"] = metrics

    def fetch():
        return float(state["m"]["cost"])

    run_steps(3)  # warmup / compile
    fetch()
    return _timed_chain(run_steps, fetch, ITERS, max(ITERS // 10, 1)) * 1e3


def bench_resnet50(compute_dtype=None, batch=None):
    """ResNet-50 train step: imgs/sec/chip and MFU (flops from XLA cost
    analysis / wall time / device peak). ``compute_dtype="bfloat16"`` runs
    mixed precision: f32 master params, bf16 forward/backward feeding the
    MXU at twice the f32 rate. ``batch`` overrides RESNET_BATCH (the bf16
    run uses 256 per the round-3 verdict: small batches under-fill the
    MXU)."""
    batch = batch or RESNET_BATCH
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.models import resnet
    from paddle_tpu.optim import Momentum
    from paddle_tpu.trainer import SGD

    dsl.reset()
    cost, out, _ = resnet(depth=50, classes=1000, image_size=224)
    trainer = SGD(cost=cost,
                  update_equation=Momentum(learning_rate=0.1, momentum=0.9),
                  compute_dtype=compute_dtype)

    rng = np.random.RandomState(0)
    feed = {
        "image": Argument(value=jnp.asarray(
            rng.rand(batch, 224 * 224 * 3), jnp.float32)),
        "label": Argument(value=jnp.asarray(
            rng.randint(0, 1000, size=batch), jnp.int32)),
    }

    key = jax.random.PRNGKey(0)
    lowered = jax.jit(
        lambda p, o, f, k: trainer._train_step(p, o, f, k, 0)).lower(
            trainer.params, trainer.opt_state, feed, key)
    compiled = lowered.compile()
    cost_an = compiled.cost_analysis()
    if isinstance(cost_an, list):  # older jax returns [dict]
        cost_an = cost_an[0] if cost_an else {}
    flops_per_step = float((cost_an or {}).get("flops", 0.0))

    state = {"params": trainer.params, "opt": trainer.opt_state, "m": None}

    def run_steps(n):
        for _ in range(n):
            state["params"], state["opt"], state["m"] = compiled(
                state["params"], state["opt"], feed, key)

    def fetch():
        return float(state["m"]["cost"])

    run_steps(2)  # warmup
    fetch()
    sec_per_step = _timed_chain(run_steps, fetch, RESNET_ITERS,
                                max(RESNET_ITERS // 10, 1))

    kind = jax.devices()[0].device_kind
    peak = PEAK_FLOPS.get(kind, DEFAULT_PEAK)
    mfu = (flops_per_step / sec_per_step / peak) if flops_per_step else None
    tag = "resnet50_bf16" if compute_dtype else "resnet50"
    return {
        f"{tag}_imgs_per_sec_per_chip": round(batch / sec_per_step, 1),
        f"{tag}_step_ms": round(sec_per_step * 1000.0, 2),
        f"{tag}_batch": batch,
        f"{tag}_mfu": round(mfu, 4) if mfu is not None else None,
        f"{tag}_flops_per_step": flops_per_step or None,
        "device_kind": kind,
    }


# The reference's published image benchmarks (`benchmark/README.md:36-61`,
# mirrored in BASELINE.md): unmodified configs from
# `/root/reference/benchmark/paddle/image/`, timed as full train steps.
IMAGE_BENCHES = {
    "alexnet": dict(feed="data", size=227, batch=128, ref_ms=334.0,
                    classes=1000),
    "googlenet": dict(feed="input", size=224, batch=128, ref_ms=1149.0,
                      classes=1000),
    "smallnet_mnist_cifar": dict(feed="data", size=32, batch=64,
                                 ref_ms=10.46, classes=10),
}


def bench_image_config(name, compute_dtype="bfloat16", iters=None):
    """Time one of the reference's own benchmark configs (unmodified) and
    compare against its published K40m ms/batch."""
    spec = IMAGE_BENCHES[name]
    iters = iters or max(RESNET_ITERS, 3)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.compat import parse_config
    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument

    dsl.reset()
    parsed = parse_config(
        f"/root/reference/benchmark/paddle/image/{name}.py",
        f"batch_size={spec['batch']}")
    trainer = parsed.build_trainer(compute_dtype=compute_dtype)

    rng = np.random.RandomState(0)
    feed = {
        spec["feed"]: Argument(value=jnp.asarray(
            rng.rand(spec["batch"], 3 * spec["size"] * spec["size"]),
            jnp.float32)),
        "label": Argument(value=jnp.asarray(
            rng.randint(0, spec["classes"], size=spec["batch"]), jnp.int32)),
    }
    key = jax.random.PRNGKey(0)
    state = {"params": trainer.params, "opt": trainer.opt_state, "m": None}

    def run_steps(n):
        for _ in range(n):
            state["params"], state["opt"], state["m"] = trainer._train_step(
                state["params"], state["opt"], feed, key, 0)

    def fetch():
        return float(state["m"]["cost"])

    run_steps(2)  # warmup / compile
    fetch()
    ms = _timed_chain(run_steps, fetch, iters, max(iters // 10, 1)) * 1e3
    tag = name.split("_")[0]
    return {
        f"{tag}_ms_per_batch": round(ms, 3),
        f"{tag}_batch": spec["batch"],
        # ours runs the TPU-idiomatic dtype; the published K40m numbers
        # are fp32 — framework-level comparison, best config per hardware
        f"{tag}_dtype": str(compute_dtype or "float32"),
        f"{tag}_vs_k40m_baseline": round(spec["ref_ms"] / ms, 3),
    }


def bench_input_pipeline(decode_ms=None, batches=None, batch_size=24):
    """Off-tunnel input-pipeline A/B: steps/s and host-blocked fraction
    for the SAME provider-fed LSTM config with the async prefetch
    pipeline off vs on, under a synthetic per-batch host decode cost
    (default 5 ms — the acceptance shape of ISSUE r06). CPU-runnable
    (``python bench.py --input-pipeline``) so BENCH_r06 has a real
    number even when the tunnel is wedged; on TPU it rides along as a
    child extra. ``data_wait_frac`` = fraction of step wall time the
    trainer thread is blocked on data (data-wait + host h2d/decode) —
    the quantity prefetch exists to drive to zero."""
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.data.provider import provider
    from paddle_tpu.data.reader import batch as batch_reader
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGD

    decode_ms = float(os.environ.get("BENCH_IP_DECODE_MS", "5.0")
                      if decode_ms is None else decode_ms)
    batches = int(os.environ.get("BENCH_IP_BATCHES", "30")
                  if batches is None else batches)
    vocab, seqlen = 1000, 32
    dsl.reset()
    cost, out, _ = lstm_text_classifier(
        vocab_size=vocab, embed_dim=32, hidden=48, num_layers=1, classes=2)
    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3))

    types = {"words": integer_value_sequence(vocab), "label": integer_value(2)}

    @provider(input_types=types, should_shuffle=False)
    def corpus(settings):
        rng = np.random.RandomState(0)
        for _ in range(batches * batch_size):
            yield (list(rng.randint(0, vocab, size=seqlen)),
                   int(rng.randint(0, 2)))

    base_feeder = DataFeeder(types, pad_multiple=seqlen)

    def slow_feeder(b):
        time.sleep(decode_ms / 1e3)  # synthetic decode cost
        return base_feeder(b)

    import itertools
    reader = batch_reader(corpus.as_reader(), batch_size, drop_last=True)
    # compile outside the measured passes (same shapes throughout:
    # fixed batch, pad_multiple = seqlen)
    trainer.train(lambda: itertools.islice(reader(), 2),
                  feeder=base_feeder, num_passes=1)

    def measure(async_on):
        trainer.train(reader, feeder=slow_feeder, num_passes=1,
                      async_load_data=async_on)
        s = trainer.step_breakdown()
        return (s["steps_per_sec"],
                s["data_wait_frac"] + s["h2d_frac"], s["steps"])

    sync_sps, sync_wait, n1 = measure(False)
    async_sps, async_wait, n2 = measure(True)
    return {
        "input_pipeline_steps_per_sec": round(async_sps, 3),
        "input_pipeline_steps_per_sec_sync": round(sync_sps, 3),
        "input_pipeline_speedup": round(async_sps / sync_sps, 3)
        if sync_sps else None,
        "data_wait_frac": round(async_wait, 4),
        "data_wait_frac_sync": round(sync_wait, 4),
        "input_pipeline_decode_ms": decode_ms,
        "input_pipeline_batches": min(n1, n2),
        "input_pipeline_batch_size": batch_size,
        "input_pipeline_recompiles": trainer.recompile_guard.count,
    }


def bench_zero1(batches=None, batch_size=64):
    """ZeRO-1 A/B: the SAME LSTM-classifier config trained over the full
    device mesh with the replicated optimizer update vs the sharded one
    (``--use_zero1``), reporting steps/s and the per-device
    param/optimizer-slot byte split from ``utils/profiler.memory_stats``.
    CPU-runnable off-tunnel (``python bench.py --zero1`` forces the
    8-virtual-device CPU mesh and writes BENCH_r07.json); on TPU it rides
    along as a child extra over the real mesh. Adam (2 slots) is the
    headline shape: slot bytes per device should drop ~N× on an N-way
    data axis."""
    import jax
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel import create_mesh
    from paddle_tpu.trainer import SGD
    from paddle_tpu.utils.profiler import memory_stats

    batches = int(os.environ.get("BENCH_Z1_BATCHES", "20")
                  if batches is None else batches)
    vocab, seqlen = 5000, 32
    n_dev = len(jax.devices())
    mesh = create_mesh(n_data=n_dev)

    types = {"words": integer_value_sequence(vocab),
             "label": integer_value(2)}
    rng = np.random.RandomState(0)
    data = [(list(rng.randint(0, vocab, size=seqlen)),
             int(rng.randint(0, 2))) for _ in range(batch_size)]
    feeder = DataFeeder(types, pad_multiple=seqlen)

    def reader():
        for _ in range(batches):
            yield data

    def build(zero1):
        dsl.reset()
        cost, out, _ = lstm_text_classifier(
            vocab_size=vocab, embed_dim=64, hidden=96, num_layers=1,
            classes=2)
        tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
                 mesh=mesh, seed=0)
        # compile + zero1 conversion outside the measured passes
        tr.train(lambda: iter([data, data]), feeder=feeder, num_passes=1,
                 zero1=zero1)
        return tr

    trainers = {False: build(False), True: build(True)}
    best = {False: 0.0, True: 0.0}
    # interleaved best-of-R passes: this host's throughput drifts by tens
    # of percent on the scale of one pass (shared box, one core), so a
    # single A/B pair is meaningless — like _timed_chain's min-of-runs,
    # each mode keeps its best pass and the modes alternate so drift
    # hits both equally
    for _ in range(int(os.environ.get("BENCH_Z1_ROUNDS", "3"))):
        for zero1, tr in trainers.items():
            tr.train(reader, feeder=feeder, num_passes=1, zero1=zero1)
            best[zero1] = max(best[zero1],
                              tr.step_breakdown()["steps_per_sec"])
    rep_sps, z_sps = best[False], best[True]
    rep_mem = memory_stats(trainers[False].params, trainers[False].opt_state)
    z_mem = memory_stats(trainers[True].params, trainers[True].opt_state)
    out = {
        "zero1_devices": n_dev,
        "zero1_optimizer": "adam",
        "zero1_steps_per_sec": round(z_sps, 3),
        "replicated_steps_per_sec": round(rep_sps, 3),
        "zero1_vs_replicated_steps": (round(z_sps / rep_sps, 3)
                                      if rep_sps else None),
        "replicated_slot_bytes_per_device": rep_mem["slot_bytes_per_device"],
        "zero1_slot_bytes_per_device": z_mem["slot_bytes_per_device"],
        "zero1_slot_bytes_reduction": round(
            rep_mem["slot_bytes_per_device"]
            / max(z_mem["slot_bytes_per_device"], 1), 2),
        "param_bytes_per_device": z_mem["param_bytes_per_device"],
        "zero1_batches": batches,
        "zero1_batch_size": batch_size,
    }
    for tag, mem in (("replicated", rep_mem), ("zero1", z_mem)):
        if "device_peak_bytes" in mem:
            out[f"{tag}_device_peak_bytes"] = mem["device_peak_bytes"]
    return out


def bench_fsdp(batches=None, batch_size=64):
    """Full-FSDP A/B: the SAME LSTM-classifier config trained at the
    same data-parallel degree with replicated parameters (the whole
    device set on the ``data`` axis) vs flat-packed 1/N parameters
    (the whole set on the ``fsdp`` axis, ``--fsdp``), reporting
    steps/s and the per-device param/slot byte split from
    ``utils/profiler.memory_stats``. The param-bytes ratio is ASSERTED
    ~N× in-bench (the ISSUE 15 acceptance claim, the same figure the
    PT602 law pins on the audited fsdp_train program); the step-time
    ratio is recorded honestly — on the 1-core virtual mesh the
    per-layer gathers are pure dispatch overhead with no memory to
    save, so expect <1×; on a real TPU the gathers ride ICI and the
    ratio is the number to watch. CPU-runnable off-tunnel
    (``python bench.py --fsdp`` writes BENCH_r17.json); on TPU it
    rides along as a child extra over the real mesh."""
    import jax
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel import create_mesh
    from paddle_tpu.trainer import SGD
    from paddle_tpu.utils.profiler import memory_stats

    batches = int(os.environ.get("BENCH_FSDP_BATCHES", "20")
                  if batches is None else batches)
    vocab, seqlen = 5000, 32
    n_dev = len(jax.devices())
    meshes = {False: create_mesh(n_data=n_dev),
              True: create_mesh(n_fsdp=n_dev)}

    types = {"words": integer_value_sequence(vocab),
             "label": integer_value(2)}
    rng = np.random.RandomState(0)
    data = [(list(rng.randint(0, vocab, size=seqlen)),
             int(rng.randint(0, 2))) for _ in range(batch_size)]
    feeder = DataFeeder(types, pad_multiple=seqlen)

    def reader():
        for _ in range(batches):
            yield data

    def build(fsdp):
        dsl.reset()
        cost, out, _ = lstm_text_classifier(
            vocab_size=vocab, embed_dim=64, hidden=96, num_layers=1,
            classes=2)
        tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
                 mesh=meshes[fsdp], seed=0)
        # compile + packing conversion outside the measured passes
        tr.train(lambda: iter([data, data]), feeder=feeder, num_passes=1,
                 fsdp=fsdp)
        return tr

    trainers = {False: build(False), True: build(True)}
    assert trainers[True]._fsdp is not None, "fsdp stood down in-bench"
    best = {False: 0.0, True: 0.0}
    # interleaved best-of-R passes (the host-drift rule: each mode
    # keeps its best pass, modes alternate so drift hits both equally)
    for _ in range(int(os.environ.get("BENCH_FSDP_ROUNDS", "3"))):
        for fsdp, tr in trainers.items():
            tr.train(reader, feeder=feeder, num_passes=1, fsdp=fsdp)
            best[fsdp] = max(best[fsdp],
                             tr.step_breakdown()["steps_per_sec"])
    rep_sps, f_sps = best[False], best[True]
    rep_mem = memory_stats(trainers[False].params,
                           trainers[False].opt_state)
    f_mem = memory_stats(trainers[True].params, trainers[True].opt_state)
    # the honest replicated denominator is the FULL model from shapes:
    # a trained run's placed bytes can be understated when XLA's output
    # propagation opportunistically shards a param output over data
    rep_mem["param_bytes_per_device"] = sum(
        int(np.prod(v.shape)) * v.dtype.itemsize
        for v in trainers[False]._params_for_save().values())
    p_ratio = (rep_mem["param_bytes_per_device"]
               / max(f_mem["param_bytes_per_device"], 1))
    # the acceptance claim is a correctness property, not a perf
    # number: assert it in-bench so a drifted artifact can't hide it.
    # The bar scales with the REAL mesh (an on-chip capture may have
    # 4 devices, where ~4x is perfect and 6.0 would always fail)
    assert p_ratio > 0.75 * n_dev, (
        f"fsdp param bytes/device only dropped {p_ratio:.2f}x on the "
        f"{n_dev}-way fsdp axis (want ~{n_dev}x)")
    out = {
        "fsdp_devices": n_dev,
        "fsdp_optimizer": "adam",
        "fsdp_steps_per_sec": round(f_sps, 3),
        "replicated_steps_per_sec": round(rep_sps, 3),
        "fsdp_vs_replicated_steps": (round(f_sps / rep_sps, 3)
                                     if rep_sps else None),
        "replicated_param_bytes_per_device":
            rep_mem["param_bytes_per_device"],
        "fsdp_param_bytes_per_device": f_mem["param_bytes_per_device"],
        "fsdp_param_bytes_reduction": round(p_ratio, 2),
        "replicated_slot_bytes_per_device":
            rep_mem["slot_bytes_per_device"],
        "fsdp_slot_bytes_per_device": f_mem["slot_bytes_per_device"],
        "fsdp_slot_bytes_reduction": round(
            rep_mem["slot_bytes_per_device"]
            / max(f_mem["slot_bytes_per_device"], 1), 2),
        "fsdp_batches": batches,
        "fsdp_batch_size": batch_size,
    }
    for tag, mem in (("replicated", rep_mem), ("fsdp", f_mem)):
        if "device_peak_bytes" in mem:
            out[f"{tag}_device_peak_bytes"] = mem["device_peak_bytes"]
    return out


def bench_overlap(batches=None, batch_size=64):
    """FSDP gather-overlap x fused-kernel 2x2 A/B (r18): the SAME
    LSTM-classifier config trained on the fsdp mesh under every
    combination of {sync, overlap-forced} gather spelling x {inline,
    fused} LSTM-cell + optimizer kernels. Reports each arm's best-of
    steps/s (interleaved rounds, the host-drift rule) plus the
    exposed-collective split from ``StepBreakdown``: the sync spelling
    exposes every gather + reduce (2 per layer), the double-buffered
    chain exposes only the first gather and last reduce — the
    ``fsdp_exposed_*`` keys are the structural claim a 1-core CPU
    can certify even though its step-time ratio is dispatch-bound
    (on ICI the step time is where the overlap pays). All four arms'
    final params are ASSERTED bitwise identical in-bench — the
    overlap chain is an ``optimization_barrier`` (identity on
    values) and the fused kernels' fallback spelling IS the inline
    math, so a nonzero diff is a correctness bug, not noise.
    CPU-runnable off-tunnel (``python bench.py --overlap`` writes
    BENCH_r18.json); rides the tpu_watch capture as a child extra."""
    import jax
    import numpy as np
    from paddle_tpu import kernels
    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.optim import Adam
    from paddle_tpu.optim import zero1
    from paddle_tpu.parallel import create_mesh
    from paddle_tpu.trainer import SGD

    batches = int(os.environ.get("BENCH_OVERLAP_BATCHES", "12")
                  if batches is None else batches)
    vocab, seqlen = 5000, 32
    n_dev = len(jax.devices())
    mesh = create_mesh(n_fsdp=n_dev)

    types = {"words": integer_value_sequence(vocab),
             "label": integer_value(2)}
    rng = np.random.RandomState(0)
    data = [(list(rng.randint(0, vocab, size=seqlen)),
             int(rng.randint(0, 2))) for _ in range(batch_size)]
    feeder = DataFeeder(types, pad_multiple=seqlen)

    def reader():
        for _ in range(batches):
            yield data

    def arm_ctx(overlap, fused):
        """The trace-time switches an arm runs under — held for BOTH
        the compiling warmup and the timed passes ("force"/"off"
        rather than auto so the A/B is honest on CPU too)."""
        import contextlib
        st = contextlib.ExitStack()
        st.enter_context(
            zero1.overlap_spelling("force" if overlap else "off"))
        st.enter_context(kernels.fused_rnn(fused))
        st.enter_context(kernels.fused_optimizer(fused))
        return st

    def build(overlap, fused):
        dsl.reset()
        cost, out, _ = lstm_text_classifier(
            vocab_size=vocab, embed_dim=64, hidden=96, num_layers=1,
            classes=2)
        tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
                 mesh=mesh, seed=0)
        with arm_ctx(overlap, fused):
            # compile + packing conversion outside the measured passes
            tr.train(lambda: iter([data, data]), feeder=feeder,
                     num_passes=1, fsdp=True, fsdp_overlap=overlap)
        return tr

    arms = [(False, False), (True, False), (False, True), (True, True)]
    trainers = {a: build(*a) for a in arms}
    best = {a: 0.0 for a in arms}
    for _ in range(int(os.environ.get("BENCH_OVERLAP_ROUNDS", "2"))):
        for a, tr in trainers.items():
            with arm_ctx(*a):
                tr.train(reader, feeder=feeder, num_passes=1)
            best[a] = max(best[a],
                          tr.step_breakdown()["steps_per_sec"])
    # the acceptance claim is bitwise neutrality of BOTH planes:
    # every arm must land on the baseline's exact trajectory
    base = {k: np.asarray(jax.device_get(v)) for k, v in
            trainers[(False, False)]._params_for_save().items()}
    for a in arms[1:]:
        for k, v in trainers[a]._params_for_save().items():
            assert np.array_equal(base[k], np.asarray(jax.device_get(v))), \
                f"arm overlap={a[0]} fused={a[1]} diverged at {k}"
    sb_off = trainers[(False, False)].step_breakdown()
    sb_on = trainers[(True, False)].step_breakdown()
    with arm_ctx(True, False):
        peak_overlap = trainers[(True, False)]._gather_peak()
    with arm_ctx(False, False):
        peak_sync = trainers[(False, False)]._gather_peak()
    return {
        "overlap_devices": n_dev,
        "overlap_off_steps_per_sec": round(best[(False, False)], 3),
        "overlap_on_steps_per_sec": round(best[(True, False)], 3),
        "overlap_vs_sync_steps": (
            round(best[(True, False)] / best[(False, False)], 3)
            if best[(False, False)] else None),
        "fused_steps_per_sec": round(best[(False, True)], 3),
        "overlap_fused_steps_per_sec": round(best[(True, True)], 3),
        "exposed_collectives_overlap_off":
            int(sb_off["fsdp_exposed_collectives"]),
        "exposed_collectives_overlap_on":
            int(sb_on["fsdp_exposed_collectives"]),
        "exposed_comm_frac_overlap_off":
            round(sb_off["fsdp_exposed_comm_frac"], 4),
        "exposed_comm_frac_overlap_on":
            round(sb_on["fsdp_exposed_comm_frac"], 4),
        "overlap_gathers_per_step": int(sb_on["fsdp_gathers_per_step"]),
        "overlap_gather_peak_bytes": int(peak_overlap or 0),
        "sync_gather_peak_bytes": int(peak_sync or 0),
        "overlap_bitwise_identical": True,
        "overlap_batches": batches,
        "overlap_batch_size": batch_size,
    }


def bench_pipeline(batches=None, batch_size=64, hidden=256, n_stages=4,
                   layers_per_stage=4, microbatches=None):
    """Pipeline-parallel A/B: the SAME deep-MLP config (per-layer device
    attrs, `n_stages` stages x `layers_per_stage` fc layers) trained
    unpipelined over a pure-DP mesh vs pipelined over a (data, pipe)
    mesh with the GPipe schedule (`--parallel_nn`), interleaved best-of-R
    per the host-drift rules (CLAUDE.md). Reports steps/s both modes, the
    bubble-fraction estimate from `utils/profiler.pipeline_bubble_stats`,
    and the per-device body-parameter bytes (the stage-stacked layout
    holds 1/S per device). CPU-runnable off-tunnel
    (``python bench.py --pipeline`` -> BENCH_r08.json); on real ICI the
    ppermute hand-off overlaps compute — on the 1-core virtual mesh the
    schedule's win cannot show, so the honest headline here is
    correctness + bubble accounting, with steps/s recorded for drift
    context."""
    import jax
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel import create_mesh
    from paddle_tpu.trainer import SGD
    from paddle_tpu.utils.profiler import memory_stats

    batches = int(os.environ.get("BENCH_PIPE_BATCHES", "12")
                  if batches is None else batches)
    n_dev = len(jax.devices())
    S = min(n_stages, n_dev)
    n_data = max(n_dev // S, 1)
    M = microbatches or int(os.environ.get("BENCH_PIPE_MICROBATCHES", "8"))

    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    X = rng.randn(batch_size, hidden).astype(np.float32)
    Y = rng.randint(0, 10, size=batch_size).astype(np.int32)
    feed = {"x": Argument(value=jnp.asarray(X)),
            "label": Argument(value=jnp.asarray(Y))}

    def reader():
        for _ in range(batches):
            yield feed

    def build(pipelined):
        dsl.reset()
        x = dsl.data(name="x", size=hidden)
        lbl = dsl.data(name="label", size=10)
        h = x
        for s in range(S):
            for j in range(layers_per_stage):
                h = dsl.fc(input=h, size=hidden, act="tanh",
                           name=f"blk{s}_{j}", layer_attr={"device": s})
        out = dsl.fc(input=h, size=10, act="softmax", name="out")
        cost = dsl.classification_cost(input=out, label=lbl)
        mesh = (create_mesh(n_data=n_data, n_pipe=S) if pipelined
                else create_mesh(n_data=n_dev, n_model=1))
        tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
                 mesh=mesh, seed=0)
        # compile outside the measured passes
        tr.train(lambda: iter([feed, feed]), num_passes=1,
                 pipeline={"microbatches": M} if pipelined else None)
        return tr

    trainers = {False: build(False), True: build(True)}
    best = {False: 0.0, True: 0.0}
    for _ in range(int(os.environ.get("BENCH_PIPE_ROUNDS", "3"))):
        for pipelined, tr in trainers.items():
            tr.train(reader, num_passes=1)
            best[pipelined] = max(best[pipelined],
                                  tr.step_breakdown()["steps_per_sec"])
    pipe_tr = trainers[True]
    s = pipe_tr.step_breakdown()
    body_keys = pipe_tr._pipe.stacked_keys() if pipe_tr._pipe else []
    pipe_body = memory_stats({k: pipe_tr.params[k] for k in body_keys})
    flat = trainers[False]
    flat_body = memory_stats({k: v for k, v in flat.params.items()
                              if k.startswith("_blk")})
    return {
        "pipeline_devices": n_dev,
        "pipeline_stages": s.get("pipeline_stages", S),
        "pipeline_microbatches": s.get("pipeline_microbatches", M),
        "pipeline_bubble_frac": round(s.get("pipeline_bubble_frac", 0.0),
                                      4),
        "pipeline_bubble_frac_per_stage": [
            round(v, 4) for v in s.get("pipeline_bubble_frac_per_stage",
                                       [])],
        "pipeline_steps_per_sec": round(best[True], 3),
        "unpipelined_steps_per_sec": round(best[False], 3),
        "pipeline_vs_unpipelined_steps": (
            round(best[True] / best[False], 3) if best[False] else None),
        "pipeline_body_param_bytes_per_device":
            pipe_body["param_bytes_per_device"],
        "unpipelined_body_param_bytes_per_device":
            flat_body["param_bytes_per_device"],
        "pipeline_body_param_bytes_reduction": round(
            flat_body["param_bytes_per_device"]
            / max(pipe_body["param_bytes_per_device"], 1), 2),
        "pipeline_batches": batches,
        "pipeline_batch_size": batch_size,
        "pipeline_hidden": hidden,
        "pipeline_layers_per_stage": layers_per_stage,
    }


def bench_serving(n_requests=None, rounds=None):
    """Serving A/B: the SAME LSTM-classifier deploy model behind the
    dynamic micro-batching engine (max_batch=8, small coalesce window)
    vs batch-size-1 serving (max_batch=1 — every request its own device
    launch), under an identical synthetic OPEN-LOOP load (arrivals on a
    fixed clock, independent of completions — the regime where queueing
    either explodes or doesn't). Interleaved best-of-R per CLAUDE.md's
    host-drift rule. Reports completed-requests/s and the p50/p99 total
    latency from the serving metrics plane, plus batch occupancy and the
    guard-asserted compile count. The offered rate is calibrated to ~2x
    the measured single-request service rate, so the unbatched mode MUST
    queue: batching's win is throughput at *bounded* p99, not a faster
    single request. CPU-runnable (``python bench.py --serving`` ->
    BENCH_r09.json); rides along as a TPU child extra."""
    import numpy as np
    from paddle_tpu.config import dsl
    from paddle_tpu.data import integer_value, integer_value_sequence
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.serving import ServingEngine, ServingPredictor
    from paddle_tpu.trainer.trainer import Topology

    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "64")
                     if n_requests is None else n_requests)
    rounds = int(os.environ.get("BENCH_SERVE_ROUNDS", "3")
                 if rounds is None else rounds)
    vocab, seqlen = 1000, 32
    dsl.reset()
    cost, out, _ = lstm_text_classifier(
        vocab_size=vocab, embed_dim=32, hidden=48, num_layers=1, classes=2)
    topo = Topology(cost)
    import jax
    net = topo.network
    params = net.init_params(jax.random.PRNGKey(0))
    feeding = {"words": integer_value_sequence(vocab),
               "label": integer_value(2)}
    rng = np.random.RandomState(0)

    def mk_sample():
        return (list(rng.randint(0, vocab, size=seqlen)),
                int(rng.randint(0, 2)))

    samples = [mk_sample() for _ in range(n_requests)]

    def build(max_batch):
        pred = ServingPredictor(
            topo.graph, params, [out.name], feeding,
            batch_buckets=[b for b in (1, 2, 4, 8) if b <= max_batch],
            length_buckets=[seqlen])
        eng = ServingEngine(pred, max_batch=max_batch,
                            batch_timeout_ms=2.0,
                            queue_depth=n_requests + 8)
        eng.start(warmup=True)
        return eng

    engines = {"batched": build(8), "unbatched": build(1)}

    # calibrate the open-loop rate off the UNBATCHED engine's sequential
    # service time (max_batch=1 dispatches immediately, so this is the
    # true per-request cost with no coalescing window in it); offer ~2x
    # that rate to both modes — the regime where batch-size-1 serving
    # must queue and dynamic batching must absorb
    t0 = time.perf_counter()
    for _ in range(10):
        engines["unbatched"].infer(samples[0])
    single_ms = (time.perf_counter() - t0) / 10 * 1e3
    interval = single_ms / 1e3 / 2.0
    # fresh metrics for BOTH modes so the published p50/p99/occupancy
    # reflect only the measured open-loop rounds (the 10 zero-queue
    # calibration requests would otherwise skew the unbatched reservoir)
    from paddle_tpu.serving import ServingMetrics
    for eng in engines.values():
        eng.metrics = ServingMetrics()

    def run(eng):
        from paddle_tpu.serving import ServingError
        reqs = []
        t_start = time.perf_counter()
        for i, s in enumerate(samples):
            target = t_start + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            try:
                reqs.append(eng.submit(s))
            except ServingError:
                # shed / dead worker: not-ok, but the A/B must still
                # finish and report (a dead engine reads as ~zero
                # throughput + its fatal in hot_path_recompiles)
                pass
        answered = [r.event.wait(120.0) for r in reqs]
        done = time.perf_counter()
        # only requests that were actually ANSWERED cleanly count — a
        # hung/dead engine must read as zero throughput, not success
        ok = sum(1 for got, r in zip(answered, reqs)
                 if got and r.error is None)
        return ok / (done - t_start)

    best = {}
    for _ in range(rounds):
        for mode, eng in engines.items():
            tput = run(eng)
            best[mode] = max(best.get(mode, 0.0), tput)
    res = {"serving_requests": n_requests,
           "serving_open_loop_interval_ms": round(interval * 1e3, 3),
           "serving_batched_rps": round(best["batched"], 2),
           "serving_unbatched_rps": round(best["unbatched"], 2),
           "serving_batched_vs_unbatched_rps": round(
               best["batched"] / max(best["unbatched"], 1e-9), 3)}
    for mode, eng in engines.items():
        snap = eng.metrics.snapshot()
        lat = snap["latency_ms"]["total"]
        res[f"serving_{mode}_p50_ms"] = lat["p50_ms"]
        res[f"serving_{mode}_p99_ms"] = lat["p99_ms"]
        res[f"serving_{mode}_queue_wait_p99_ms"] = (
            snap["latency_ms"]["queue_wait"]["p99_ms"])
        res[f"serving_{mode}_occupancy"] = snap["batch_occupancy"]["mean"]
        res[f"serving_{mode}_batches"] = snap["batches_total"]
        # the hardened guard raises (killing the worker) on any hot-path
        # compile — a clean worker proves zero; a dead one is recorded
        res[f"serving_{mode}_hot_path_recompiles"] = (
            0 if eng.fatal is None else repr(eng.fatal)[:120])
        eng.shutdown()
    return res


def bench_serving_quant(rounds=None, calls=None):
    """Quantized-serving three-way A/B: the SAME LSTM-classifier deploy
    model merged fp32 / ``--quantize=bf16`` / ``--quantize=int8``, each
    artifact loaded by the serving predictor exactly as deploy would
    (storage-dtype leaves + fused dequant view) and WARMED THROUGH THE
    ACCURACY GATE in-bench — a drifted quantized artifact aborts the
    bench instead of publishing a speedup for a model that answers
    wrong. Interleaved best-of-R per CLAUDE.md's host-drift rule: the
    three precision tiers alternate within every round and each
    reports its best per-round median batch-predict latency. The gate
    deltas and verdict ride the artifact (PT401's ``serving_quant``
    schema refuses the speedup without them). CPU-runnable
    (``python bench.py --quant`` -> BENCH_r19.json); rides along as a
    TPU child extra."""
    import shutil
    import tempfile

    import numpy as np

    import jax
    from paddle_tpu import quant as quant_lib
    from paddle_tpu.config import dsl
    from paddle_tpu.data import integer_value, integer_value_sequence
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.serving import ServingPredictor
    from paddle_tpu.trainer.merge_model import merge_model
    from paddle_tpu.trainer.trainer import Topology

    rounds = int(os.environ.get("BENCH_QUANT_ROUNDS", "3")
                 if rounds is None else rounds)
    calls = int(os.environ.get("BENCH_QUANT_CALLS", "12")
                if calls is None else calls)
    vocab, seqlen = 1000, 32
    dsl.reset()
    cost, out, _ = lstm_text_classifier(
        vocab_size=vocab, embed_dim=32, hidden=48, num_layers=1,
        classes=2)
    topo = Topology(cost)
    params = topo.network.init_params(jax.random.PRNGKey(0))
    params = {k: np.asarray(v) for k, v in params.items()}
    feeding = {"words": integer_value_sequence(vocab),
               "label": integer_value(2)}
    golden = quant_lib.golden_section(topo.graph, params, [out.name],
                                      feeding)
    rng = np.random.RandomState(0)
    rows = [(list(rng.randint(0, vocab, size=seqlen)),
             int(rng.randint(0, 2))) for _ in range(8)]

    preds = {}
    versions = {}
    tmp = tempfile.mkdtemp(prefix="bench_quant_")
    try:
        for dt in ("fp32", "bf16", "int8"):
            path = os.path.join(tmp, f"{dt}.ptmodel")
            if dt == "fp32":
                merge_model(path, topo.graph, params,
                            outputs=[out.name])
            else:
                q, meta = quant_lib.quantize_params(params, dt,
                                                    sparse_names=set())
                merge_model(path, topo.graph, q, outputs=[out.name],
                            quant=meta, golden=golden)
            pred = ServingPredictor.from_merged(
                path, feeding, batch_buckets=[8],
                length_buckets=[seqlen])
            # warmup REPLAYS THE GOLDEN GATE for the quantized tiers:
            # a drifted artifact raises QuantGateError right here
            pred.warmup()
            preds[dt] = pred
            versions[dt] = pred.model_version

        def one_call(pred):
            t0 = time.perf_counter()
            pred.predict_rows(rows)
            return (time.perf_counter() - t0) * 1e3

        best = {}
        for _ in range(rounds):
            for dt, pred in preds.items():  # interleaved within round
                ms = sorted(one_call(pred) for _ in range(calls))
                med = ms[len(ms) // 2]
                best[dt] = min(best.get(dt, float("inf")), med)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    assert len(set(versions.values())) == 3, (
        f"precision tiers must publish distinct versions: {versions}")
    res = {"quant_calls": calls, "quant_rows_per_call": len(rows),
           "quant_model_versions": versions}
    for dt in ("fp32", "bf16", "int8"):
        res[f"quant_{dt}_p50_ms"] = round(best[dt], 3)
    res["quant_bf16_vs_fp32"] = round(best["bf16"] / best["fp32"], 3)
    res["quant_int8_vs_fp32"] = round(best["int8"] / best["fp32"], 3)
    gates = {dt: preds[dt].quant_gate for dt in ("bf16", "int8")}
    for dt, g in gates.items():
        res[f"quant_gate_delta_{dt}"] = g["max_delta"]
        res[f"quant_gate_tol_{dt}"] = g["tol"]
    res["quant_gate_passed"] = all(g["passed"] for g in gates.values())
    return res


def bench_decode(rounds=None, calls=None):
    """Decode A/B (two axes, interleaved best-of-R per CLAUDE.md's
    host-drift rule):

    1. **Early-exit chunked search vs full scan** — the same beam search
       over a short-output workload (every request finishes in <= 2
       steps, max_length 64): the chunked ``lax.while_loop`` search
       exits at the first chunk boundary where every beam finished, so
       it pays ~chunk steps where the full scan pays 64. Tokens/scores
       are asserted byte-identical between modes (the exactness claim of
       ``docs/generation.md``), and steps-executed are reported.
    2. **Continuous batching vs convoy batching** — the same serving
       engine over a mixed burst (mostly-short + a long tail): convoy
       mode holds every coalesced batch until its slowest lane's search
       returns; continuous mode retires finished lanes and admits queued
       requests at every chunk boundary. Completed-requests/s, plus lane
       occupancy / mid-decode admissions / steps saved from the metrics
       plane, and the hardened-guard recompile assertion for both.

    The decode model is length-controlled by construction (EOS logit =
    3 * sum(memory), memory boots from tanh(2*src)): positive src
    finishes in <= 2 steps, negative src never emits EOS and runs the
    full max_length — a deterministic convoy workload with margins too
    fat for cross-batch-width numeric drift to flip a token. CPU-runnable
    (``python bench.py --decode`` -> BENCH_r10.json); rides the TPU
    capture as a child extra."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.config import dsl
    from paddle_tpu.core.generation import SequenceGenerator
    from paddle_tpu.core.network import Network
    from paddle_tpu.core.registry import get_layer_impl
    from paddle_tpu.data import dense_vector
    from paddle_tpu.serving import ServingEngine, ServingPredictor

    rounds = int(os.environ.get("BENCH_DECODE_ROUNDS", "3")
                 if rounds is None else rounds)
    calls = int(os.environ.get("BENCH_DECODE_CALLS", "4")
                if calls is None else calls)
    # sized so step compute (not per-chunk host dispatch) dominates on
    # the 1-core host — the regime a real accelerator is always in
    V, E, H, K, L, CHUNK, B = 2048, 64, 256, 4, 64, 8, 8

    dsl.reset()
    src = dsl.data("src", size=H)
    boot = dsl.fc(src, size=H, act="tanh", name="boot", bias_attr=False)

    def step(prev_emb):
        m = dsl.memory(name="h", size=H, boot_layer=boot)
        h = dsl.fc([prev_emb, m], size=H, act="tanh", name="h",
                   bias_attr=False)
        return dsl.fc(h, size=V, act="softmax", name="prob",
                      bias_attr=False)

    dsl.beam_search(
        step, [dsl.GeneratedInput(size=V, embedding_name="gen_emb",
                                  embedding_size=E)],
        bos_id=0, eos_id=1, beam_size=K, max_length=L, name="gen")
    graph = dsl.current_graph()
    net = Network(graph, outputs=["boot"])
    params = dict(net.init_params(jax.random.PRNGKey(0)))
    boot_key = next(k for k in params if "boot" in k)
    params[boot_key] = jnp.asarray(2.0 * np.eye(H, dtype=np.float32))
    for _, spec in get_layer_impl("beam_search_group").params(
            graph.layers["gen"], []).items():
        params[spec.absolute_name] = jnp.zeros(spec.shape, jnp.float32)
    params["_h.w1"] = jnp.asarray(np.eye(H, dtype=np.float32))
    u = np.zeros((H, V), np.float32)
    u[:, 1] = 3.0
    params["_prob.w0"] = jnp.asarray(u)
    params["gen_emb"] = jnp.zeros((V, E), jnp.float32)

    res = {"decode_max_length": L, "decode_chunk": CHUNK,
           "decode_beam": K, "decode_batch": B}

    # ---- axis 1: chunked early-exit vs full scan ---------------------
    from paddle_tpu.core.argument import Argument
    gen = SequenceGenerator(graph, "gen")
    srcv = jnp.asarray(np.ones((B, H), np.float32))  # all-short workload
    outer = net.apply(params, {"src": Argument(value=srcv)})

    def run_gen(full_scan):
        t, s, ln = gen.generate(params, outer, full_scan=full_scan,
                                decode_chunk=CHUNK)
        jax.block_until_ready(s)
        return np.asarray(t), np.asarray(s), gen.last_info

    full_out = run_gen(True)       # also warms both compiles
    chunk_out = run_gen(False)
    res["decode_bitwise_identical"] = bool(
        np.array_equal(full_out[0], chunk_out[0])
        and np.array_equal(full_out[1], chunk_out[1]))
    res["decode_steps_full"] = full_out[2]["decode_steps"]
    res["decode_steps_chunked"] = chunk_out[2]["decode_steps"]
    best = {"full": 0.0, "chunked": 0.0}
    for _ in range(rounds):
        for mode, fs in (("full", True), ("chunked", False)):
            t0 = time.perf_counter()
            for _ in range(calls):
                run_gen(fs)
            dt = time.perf_counter() - t0
            best[mode] = max(best[mode], calls * B / dt)
    res["decode_full_scan_gen_per_s"] = round(best["full"], 2)
    res["decode_chunked_gen_per_s"] = round(best["chunked"], 2)
    res["decode_chunked_vs_full_scan"] = round(
        best["chunked"] / max(best["full"], 1e-9), 3)

    # ---- axis 2: continuous vs convoy batching -----------------------
    n_requests = int(os.environ.get("BENCH_DECODE_REQUESTS", "32"))
    rng = np.random.RandomState(0)
    samples = [(([-1.0] * H,) if rng.rand() < 0.2 else ([1.0] * H,))
               for _ in range(n_requests)]

    def build(continuous):
        pred = ServingPredictor(graph, params, ["gen"],
                                {"src": dense_vector(H)},
                                batch_buckets=[1, 2, 4, 8],
                                gen_decode_chunk=CHUNK)
        return ServingEngine(pred, max_batch=8, batch_timeout_ms=2.0,
                             queue_depth=n_requests + 8,
                             continuous_batching=continuous).start()

    engines = {"continuous": build(True), "convoy": build(False)}
    best = {}
    for _ in range(rounds):
        for mode, eng in engines.items():
            t0 = time.perf_counter()
            reqs = [eng.submit(s, kind="generate") for s in samples]
            answered = [r.event.wait(300.0) for r in reqs]
            dt = time.perf_counter() - t0
            ok = sum(1 for got, r in zip(answered, reqs)
                     if got and r.error is None)
            best[mode] = max(best.get(mode, 0.0), ok / dt)
    res["serving_convoy_rps"] = round(best["convoy"], 2)
    res["serving_continuous_rps"] = round(best["continuous"], 2)
    res["serving_continuous_vs_convoy_rps"] = round(
        best["continuous"] / max(best["convoy"], 1e-9), 3)
    for mode, eng in engines.items():
        snap = eng.metrics.snapshot()
        res[f"serving_{mode}_decode_steps_p50"] = snap["decode_steps"]["p50"]
        res[f"serving_{mode}_steps_saved_total"] = (
            snap["decode_steps_saved_total"])
        # the hardened guard raises (killing the worker) on any hot-path
        # compile — a clean worker proves zero; a dead one is recorded
        res[f"serving_{mode}_hot_path_recompiles"] = (
            0 if eng.fatal is None else repr(eng.fatal)[:120])
    res["serving_continuous_lane_occupancy"] = (
        engines["continuous"].metrics.snapshot()["lane_occupancy"]["mean"])
    res["serving_continuous_admissions"] = (
        engines["continuous"].metrics.counters[
            "continuous_admissions_total"])
    for eng in engines.values():
        eng.shutdown()
    return res


def bench_autotune(rounds=None):
    """Self-tuning A/B (``python bench.py --autotune`` -> BENCH_r21.json
    plus the two committed ``WORKLOAD_r21_*.json`` traces):

    1. **Record** — drive each canonical mix (``serving/mixes.py``:
       the bursty classifier stream and the 20%-long-tail decode
       convoy) through its engine with the admission tap installed
       (``engine.workload_recorder``), snapshot the offered stream and
       commit it as ``WORKLOAD_r21_<mix>.json`` (the PT401 family; the
       replay tests rebuild these exact fleets from the same module).
    2. **Tune** — ``GridTuner`` coordinate descent over the
       hot-applicable knob grid, every candidate landed through the
       typed ``apply_config`` path on the LIVE engine and scored by
       replaying the committed trace against the declared SLO.
    3. **A/B** — hand-set defaults vs the tuned config, interleaved
       best-of-R per CLAUDE.md's host-drift rule, on the SLO score.
       The defaults shed structurally (queue narrower than the burst),
       so the ordering is count-driven, not a latency coin flip.
    4. **Determinism** — the tuned config replayed twice more: outcome
       counts must match EXACTLY and the score spread must stay within
       ``SCORE_DRIFT_BOUND`` — asserted in-bench, same contract the
       replay tests assert.

    ``failed_non_shed`` is SUMMED over EVERY replay this bench performs
    (record drive, calibration, grid search, A/B, determinism) and
    asserted zero — a dropped request anywhere is a bug, not a tuning
    datum. Zero hot-path recompiles across the whole knob sequence is
    asserted via the hardened guard (``eng.fatal is None``)."""
    from paddle_tpu.serving import mixes
    from paddle_tpu.serving.tuner import GridTuner, SLOTarget
    from paddle_tpu.serving.workload import (SCORE_DRIFT_BOUND, Workload,
                                             WorkloadRecorder,
                                             engine_dispatch, replay,
                                             replay_score)

    rounds = int(os.environ.get("BENCH_AUTOTUNE_ROUNDS", "3")
                 if rounds is None else rounds)
    here = os.path.dirname(os.path.abspath(__file__))
    res = {"autotune_mixes": [], "autotune_workloads": [],
           "autotune_drift_bound": SCORE_DRIFT_BOUND,
           "autotune_rounds": rounds}
    failed_total = 0  # summed over EVERY replay, never best-of'd

    # every grid value sits inside the warmed bucket menu ([1, 2, 4]
    # for both mixes) — the tuner explores, the menu edge stays a 409
    specs = [
        ("short_burst", {"batch_timeout_ms": [0.5, 2.0, 4.0],
                         "max_batch": [2, 4],
                         "queue_depth": [6, 64]}),
        ("convoy", {"batch_timeout_ms": [0.5, 2.0, 8.0],
                    "max_batch": [2, 4],
                    "queue_depth": [4, 64]}),
    ]
    for mix, grid in specs:
        build, make_pacer = mixes.MIXES[mix]
        eng = build()  # the hand-set defaults — the A side
        defaults = {k: v for k, v in eng.current_config().items()
                    if k in grid}
        disp = engine_dispatch(eng)

        def apply(cfg, eng=eng):
            # the shed watermark rides the queue depth here: applying a
            # deeper queue alone leaves the incumbent watermark clamped
            # at the OLD depth (apply_config never widens it silently),
            # which would pin the tuner in a coupled valley where
            # neither knob moves the shed count on its own
            d = dict(cfg)
            if "queue_depth" in d and "shed_watermark" not in d:
                d["shed_watermark"] = d["queue_depth"]
            eng.apply_config(d)

        # ---- 1. record the offered stream through the admission tap
        tap = WorkloadRecorder()
        eng.workload_recorder = tap
        drive = replay(make_pacer(), disp)
        eng.workload_recorder = None
        failed_total += drive["failed_non_shed"]
        trace_path = os.path.join(here, f"WORKLOAD_r21_{mix}.json")
        tap.snapshot(mix).save(trace_path)
        trace = Workload.load(trace_path)  # tune the COMMITTED artifact
        assert len(trace.events) == drive["offered"]

        # SLO calibrated against a generously provisioned replay of the
        # same trace (structural: both A/B sides face the same target,
        # so host drift moves both latency factors together)
        generous = {"queue_depth": max(grid["queue_depth"]),
                    "batch_timeout_ms": min(grid["batch_timeout_ms"]),
                    "max_batch": max(grid["max_batch"])}
        apply(generous)
        cal = replay(trace, disp)
        failed_total += cal["failed_non_shed"]
        slo = SLOTarget(p99_ms=4.0 * max(cal["p99_ms"] or 1.0, 1.0),
                        max_shed_rate=0.02)

        # ---- 2. offline descent, every candidate through apply_config
        def score_fn(cfg):
            nonlocal failed_total
            apply(cfg)
            s = replay_score(trace, disp, slo, rounds=1)
            failed_total += s["failed_non_shed"]
            return s["score"]

        tuner = GridTuner(grid, score_fn, base=defaults, sweeps=2)
        tuned, _ = tuner.tune()

        # ---- 3. defaults-vs-tuned, interleaved best-of-R
        best = {"default": None, "tuned": None}
        for _ in range(rounds):
            for side, cfg in (("default", defaults), ("tuned", tuned)):
                apply(cfg)
                s = replay_score(trace, disp, slo, rounds=1)
                failed_total += s["failed_non_shed"]
                if best[side] is None or s["score"] > best[side]["score"]:
                    best[side] = s
        d, t = best["default"], best["tuned"]
        assert t["score"] > d["score"], (
            f"{mix}: tuned {tuned} scored {t['score']:.3f} <= hand-set "
            f"defaults {defaults} at {d['score']:.3f}")

        # ---- 4. in-bench determinism: counts exact, score in bounds
        apply(tuned)
        r1 = replay_score(trace, disp, slo, rounds=1)
        r2 = replay_score(trace, disp, slo, rounds=1)
        failed_total += r1["failed_non_shed"] + r2["failed_non_shed"]
        for k in ("offered", "ok", "shed", "deadline_miss"):
            assert r1[k] == r2[k], (mix, k, r1[k], r2[k])
        drift = abs(r1["score"] - r2["score"])
        assert drift <= SCORE_DRIFT_BOUND, (mix, drift)
        # the whole knob sequence rode the hardened guard: any hot-path
        # compile would have killed the worker
        assert eng.fatal is None, repr(eng.fatal)
        eng.shutdown()

        res["autotune_mixes"].append(mix)
        res["autotune_workloads"].append(os.path.basename(trace_path))
        res[f"autotune_{mix}_events"] = len(trace.events)
        res[f"autotune_{mix}_slo_p99_ms"] = round(slo.p99_ms, 3)
        res[f"autotune_{mix}_default_config"] = defaults
        res[f"autotune_{mix}_tuned_config"] = tuned
        res[f"autotune_{mix}_grid_evals"] = len(tuner.history)
        res[f"autotune_{mix}_default_score"] = round(d["score"], 4)
        res[f"autotune_{mix}_tuned_score"] = round(t["score"], 4)
        res[f"autotune_{mix}_tuned_vs_default_score"] = round(
            t["score"] / max(d["score"], 1e-9), 3)
        res[f"autotune_{mix}_default_shed"] = d["shed"]
        res[f"autotune_{mix}_tuned_shed"] = t["shed"]
        res[f"autotune_{mix}_default_p99_ms"] = round(d["p99_ms"], 3)
        res[f"autotune_{mix}_tuned_p99_ms"] = round(t["p99_ms"], 3)
        res[f"autotune_{mix}_replay_drift"] = round(drift, 4)
        res[f"autotune_{mix}_hot_path_recompiles"] = 0

    res["fleet_failed_non_shed"] = failed_total
    assert failed_total == 0, f"replays dropped {failed_total} requests"
    return res


def bench_health(batches=None, batch_size=64, rounds=None):
    """Training-health overhead A/B (``python bench.py --health`` ->
    BENCH_r16.json + HEALTH_r16.json): the SAME LSTM-classifier config
    stepped with the health plane FULLY armed — per-layer stats fused
    into EVERY step (period=1, the worst case), sentry on, JSONL
    timeline appending — vs disarmed. Interleaved best-of-R per the
    host-drift rule (each mode keeps its best pass-median step time,
    modes alternate so drift hits both): the headline is the p50
    ratio. Bitwise trajectory identity is asserted IN-BENCH: after all
    rounds both trainers must hold bit-identical parameters, or this
    raises — the overhead number is only meaningful for a telemetry
    that changed nothing."""
    import time as _time

    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGD
    from paddle_tpu.trainer import events as ev

    batches = int(os.environ.get("BENCH_HEALTH_BATCHES", "12")
                  if batches is None else batches)
    rounds = int(os.environ.get("BENCH_HEALTH_ROUNDS", "4")
                 if rounds is None else rounds)
    # hidden=256 on purpose: the param-stat reduction's cost is
    # ~constant per parameter (a handful of passes over params/grads)
    # while the step's compute scales with batch*seq*hidden^2, so a
    # toy-sized model would measure XLA:CPU's reduce throughput, not
    # the telemetry's overhead on a real training step (on TPU the
    # same reductions fuse into the update for ~free)
    vocab, seqlen = 5000, 64
    types = {"words": integer_value_sequence(vocab),
             "label": integer_value(2)}
    rng = np.random.RandomState(0)
    data = [(list(rng.randint(0, vocab, size=seqlen)),
             int(rng.randint(0, 2))) for _ in range(batch_size)]
    feeder = DataFeeder(types, pad_multiple=seqlen)

    def reader():
        for _ in range(batches):
            yield data

    import tempfile
    log_path = os.path.join(tempfile.mkdtemp(prefix="bench_health_"),
                            "timeline.jsonl")

    def build(armed):
        dsl.reset()
        cost, out, _ = lstm_text_classifier(
            vocab_size=vocab, embed_dim=64, hidden=256, num_layers=1,
            classes=2)
        tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
                 seed=0)
        health = ({"period": 1, "sentry": True,
                   "log_path": log_path} if armed else None)
        # warm/compile outside the measured passes (both variants)
        tr.train(lambda: iter([data, data]), feeder=feeder,
                 num_passes=1, health=health)
        return tr

    trainers = {False: build(False), True: build(True)}

    def timed_pass(tr):
        ts = []

        def handler(e):
            if isinstance(e, ev.BeginIteration):
                ts.append(_time.perf_counter())

        tr.train(reader, feeder=feeder, num_passes=1,
                 event_handler=handler)
        return float(np.median(np.diff(ts)))

    best = {False: float("inf"), True: float("inf")}
    for _ in range(rounds):
        for armed, tr in trainers.items():
            best[armed] = min(best[armed], timed_pass(tr))
    off_s, on_s = best[False], best[True]

    # the neutrality claim, asserted in-bench: identical batch/seed
    # streams => bit-identical parameters, or the ratio above measured
    # a telemetry that changed the training it observed
    import jax
    identical = True
    p_off = {k: np.asarray(jax.device_get(v))
             for k, v in trainers[False].params.items()}
    for k, v in trainers[True].params.items():
        if not np.array_equal(p_off[k], np.asarray(jax.device_get(v))):
            identical = False
            break
    if not identical:
        raise RuntimeError(
            "health telemetry changed the trajectory: stats-on params "
            "differ from stats-off after identical streams")

    hm = trainers[True]._health
    hm.close()
    from paddle_tpu.obs.events import load_timeline
    timeline = [r for r in load_timeline(log_path)
                if r.get("event") in ("step", "divergence")]
    snap = hm.snapshot()
    return {
        "health_period": 1,
        "health_sentry": True,
        "health_batches": batches,
        "health_rounds": rounds,
        "health_on_ms_per_step_p50": round(on_s * 1e3, 3),
        "health_off_ms_per_step_p50": round(off_s * 1e3, 3),
        "health_on_vs_off_p50": (round(on_s / off_s, 4)
                                 if off_s > 0 else None),
        "health_overhead_frac": (round(on_s / off_s - 1.0, 4)
                                 if off_s > 0 else None),
        "health_bitwise_identical": identical,
        "health_sentry_trips": snap["sentry_trips"],
        "health_timeline_events": len(timeline),
        "_health_timeline": timeline,  # stripped into HEALTH_r16.json
    }


def bench_fleet(rounds=None, n_requests=None):
    """Fleet serving A/B (``python bench.py --fleet`` -> BENCH_r13.json):

    1. **Cold start: live trace vs AOT cache** — the SAME LSTM deploy
       model built + warmed + answering its first request, (a) tracing
       every bucket variant live vs (b) deserializing the warmed menu
       from the AOT cache (``serving/aot_cache.py``). Interleaved
       best-of-R per CLAUDE.md's host-drift rule. This is the number
       that decides whether kill-and-respawn under load is a non-event:
       a respawned replica pays (b), not (a).
    2. **Kill-and-respawn under open-loop load** — three router-fronted
       replicas (each its own predictor, all warmed from the shared
       cache) under a fixed-rate open-loop request schedule; mid-run a
       seeded chaos fault kills one replica's serving worker
       (``serve_batch`` kill, the in-process SIGKILL analogue). The
       router fails the in-flight request over, ejects the replica, and
       respawns it from the cache. Reported: zero failed non-shed
       requests (asserted), fleet p50/p99 through the router, failover /
       respawn counters, and the respawn's warm time.
    """
    import tempfile
    import threading

    import numpy as np
    import jax
    from paddle_tpu.config import dsl
    from paddle_tpu.data import integer_value, integer_value_sequence
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.serving import (EngineTransport, Overloaded,
                                    ReplicaRouter, ServingEngine,
                                    ServingError, ServingPredictor)
    from paddle_tpu.testing import chaos
    from paddle_tpu.trainer.trainer import Topology

    rounds = int(os.environ.get("BENCH_FLEET_ROUNDS", "2")
                 if rounds is None else rounds)
    n_requests = int(os.environ.get("BENCH_FLEET_REQUESTS", "60")
                     if n_requests is None else n_requests)
    vocab, seqlen = 1000, 32
    dsl.reset()
    cost, out, _ = lstm_text_classifier(
        vocab_size=vocab, embed_dim=32, hidden=48, num_layers=1, classes=2)
    topo = Topology(cost)
    params = topo.network.init_params(jax.random.PRNGKey(0))
    feeding = {"words": integer_value_sequence(vocab),
               "label": integer_value(2)}
    rng = np.random.RandomState(0)

    def mk_sample():
        return (list(rng.randint(0, vocab, size=seqlen)),
                int(rng.randint(0, 2)))

    cache_dir = tempfile.mkdtemp(prefix="paddle_tpu_aot_bench_")

    def build_pred(cached: bool):
        return ServingPredictor(
            topo.graph, params, [out.name], feeding,
            batch_buckets=[1, 4], length_buckets=[seqlen],
            aot_cache=cache_dir if cached else None)

    sample = mk_sample()

    def cold_start_ms(cached: bool) -> float:
        """Build + warm + first answer, the full respawn path."""
        t0 = time.perf_counter()
        pred = build_pred(cached)
        pred.warmup()
        pred.predict_rows([sample])
        return 1e3 * (time.perf_counter() - t0)

    # prime the cache once (not timed as the cache arm — it is the live
    # arm's work product), then interleave live/cache rounds
    prime_ms = cold_start_ms(True)
    best = {"live": float("inf"), "cache": float("inf")}
    for _ in range(rounds):
        best["live"] = min(best["live"], cold_start_ms(False))
        best["cache"] = min(best["cache"], cold_start_ms(True))
    res = {
        "cold_start_live_ms": round(best["live"], 1),
        "cold_start_cache_ms": round(best["cache"], 1),
        "cold_start_live_vs_cache": round(
            best["live"] / max(best["cache"], 1e-9), 2),
        "cold_start_prime_ms": round(prime_ms, 1),
        "fleet_rounds": rounds,
    }

    # ---- kill-and-respawn under open-loop load -----------------------
    def build_engine():
        return ServingEngine(build_pred(True), max_batch=4,
                             batch_timeout_ms=2.0,
                             queue_depth=n_requests + 8
                             ).start(warmup=True)

    best_round = None
    failed_all_rounds = 0  # the zero-drop invariant is PER ROUND —
    # best-of-R applies to perf numbers, never to a correctness counter
    for _ in range(rounds):
        engines = [build_engine() for _ in range(3)]
        router = ReplicaRouter(
            [EngineTransport(e) for e in engines],
            spawn=lambda rid: EngineTransport(build_engine()),
            health_poll_ms=25.0).start()
        # calibrate the open-loop rate off sequential dispatches, then
        # offer ~2x that rate so queues form and failover runs hot
        t0 = time.perf_counter()
        for _ in range(8):
            router.dispatch(sample)
        interval = (time.perf_counter() - t0) / 8 / 2.0
        from paddle_tpu.serving import RouterMetrics
        router.metrics = RouterMetrics()
        # the seeded fault: kill whichever replica serves the Nth batch
        # mid-run; the schedule reproduces from the seed
        plan = chaos.FaultPlan(seed=13, faults=[
            {"type": "kill", "site": "serve_batch", "at": 6,
             "mode": "raise"}])
        counts = {"ok": 0, "shed": 0, "failed": 0}
        lock = threading.Lock()

        def one(s):
            from paddle_tpu.serving import Unavailable
            try:
                router.dispatch(s)
                key = "ok"
            except Unavailable:
                # NO ready replica = outage, not backpressure — it must
                # fail the zero-drop assertion (Unavailable subclasses
                # Overloaded, so this arm must come first)
                key = "failed"
            except Overloaded:
                key = "shed"  # typed backpressure is not a failure
            except ServingError:
                key = "failed"
            with lock:
                counts[key] += 1

        threads = []
        samples = [mk_sample() for _ in range(n_requests)]
        t_start = time.perf_counter()
        with chaos.chaos_plan(plan):
            for i, s in enumerate(samples):
                target = t_start + i * interval
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                th = threading.Thread(target=one, args=(s,))
                th.start()
                threads.append(th)
            for th in threads:
                th.join(120.0)
        elapsed = time.perf_counter() - t_start
        # give the health loop a beat to finish the respawn
        deadline = time.perf_counter() + 10.0
        while (time.perf_counter() < deadline
               and router.metrics.snapshot()["respawns_total"] < 1):
            time.sleep(0.05)
        snap = router.metrics.snapshot()
        health = router.fleet_health()
        round_res = {
            "fleet_requests": n_requests,
            "fleet_open_loop_interval_ms": round(interval * 1e3, 3),
            "fleet_ok": counts["ok"],
            "fleet_shed": counts["shed"],
            "fleet_failed_non_shed": counts["failed"],
            "fleet_rps": round(counts["ok"] / elapsed, 2),
            "fleet_p50_ms": snap["fleet_latency_ms"]["p50_ms"],
            "fleet_p99_ms": snap["fleet_latency_ms"]["p99_ms"],
            "fleet_failovers_total": snap["failovers_total"],
            "fleet_replica_deaths_total": snap["replica_deaths_total"],
            "fleet_respawns_total": snap["respawns_total"],
            "fleet_respawn_warm_ms": next(
                (round(r["last_spawn_ms"], 1)
                 for r in health["replicas"]
                 if r["last_spawn_ms"] is not None), None),
            "fleet_ready_after": health["ready_replicas"],
        }
        router.shutdown()
        failed_all_rounds += counts["failed"]
        # best-of across rounds: most clean answers, then lowest p99
        keyf = (round_res["fleet_ok"],
                -(round_res["fleet_p99_ms"] or 1e9))
        if best_round is None or keyf > best_round[0]:
            best_round = (keyf, round_res)
    res.update(best_round[1])
    # report (and assert) the SUM over every round: a round where the
    # kill DID fail requests must not hide behind a cleaner best-of
    res["fleet_failed_non_shed"] = failed_all_rounds
    # the acceptance invariant, asserted where the evidence is made:
    # a replica SIGKILL under load must not fail a single non-shed
    # request in ANY round (failover + respawn absorb it)
    assert failed_all_rounds == 0, res
    return res


def bench_fleet_autoscale():
    """Autoscale under a traffic ramp (``--fleet`` → BENCH_r14.json):
    one replica behind the router; open-loop traffic at ~3× its
    calibrated capacity makes the EWMA backlog cross the scale-up
    threshold, the autoscaler grows the fleet (warm via the shared AOT
    cache — this is the scale-up-latency half of the cold-start A/B),
    and sustained idle shrinks it back to the floor. Reported: the
    replica-count trajectory (must follow the ramp inside
    [min, max] — asserted), p99 through the ramp (bounded — asserted),
    zero failed non-shed (asserted), and the scale action counters.

    Honesty note (CLAUDE.md): on this 1-core host extra replicas add no
    real compute parallelism — the evidence here is the CONTROL LOOP
    (signal → sustained-threshold → bounded scaling → hysteresis back
    down), not a throughput win; on a pod each replica is its own chip.
    """
    import tempfile
    import threading

    import numpy as np
    import jax
    from paddle_tpu.config import dsl
    from paddle_tpu.data import integer_value, integer_value_sequence
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.serving import (Autoscaler, EngineTransport,
                                    InProcessFleet, Overloaded,
                                    ReplicaRouter, ServingEngine,
                                    ServingError, ServingPredictor)
    from paddle_tpu.trainer.trainer import Topology

    vocab, seqlen = 1000, 32
    n_ramp = int(os.environ.get("BENCH_AUTOSCALE_REQUESTS", "60"))
    max_replicas = 3
    dsl.reset()
    cost, out, _ = lstm_text_classifier(
        vocab_size=vocab, embed_dim=32, hidden=48, num_layers=1,
        classes=2)
    topo = Topology(cost)
    params = topo.network.init_params(jax.random.PRNGKey(0))
    feeding = {"words": integer_value_sequence(vocab),
               "label": integer_value(2)}
    rng = np.random.RandomState(0)

    def mk_sample():
        return (list(rng.randint(0, vocab, size=seqlen)),
                int(rng.randint(0, 2)))

    cache_dir = tempfile.mkdtemp(prefix="paddle_tpu_aot_scale_")

    def build_engine():
        pred = ServingPredictor(
            topo.graph, params, [out.name], feeding,
            batch_buckets=[1, 4], length_buckets=[seqlen],
            aot_cache=cache_dir)
        return ServingEngine(pred, max_batch=4, batch_timeout_ms=2.0,
                             queue_depth=n_ramp + 8
                             ).start(warmup=True)

    # scale-up latency warm-vs-cold: the FIRST engine build traces live
    # and populates the cache; every autoscale scale-up deserializes it
    t0 = time.perf_counter()
    first = build_engine()
    scaleup_cold_ms = 1e3 * (time.perf_counter() - t0)
    router = ReplicaRouter([EngineTransport(first)],
                           health_poll_ms=25.0).start()
    sample = mk_sample()
    # calibrate single-replica service time (per CLAUDE.md: no absolute
    # thresholds on a ±50%-drift host — everything relative to this)
    t0 = time.perf_counter()
    for _ in range(8):
        router.dispatch(sample)
    base_ms = 1e3 * (time.perf_counter() - t0) / 8
    from paddle_tpu.serving import RouterMetrics
    router.metrics = RouterMetrics()

    scaleup_ms = []

    def build():
        t0 = time.perf_counter()
        e = build_engine()
        scaleup_ms.append(1e3 * (time.perf_counter() - t0))
        return EngineTransport(e)

    fleet = InProcessFleet(router, build)
    counts = {"ok": 0, "shed": 0, "failed": 0}
    lock = threading.Lock()

    def one(s):
        try:
            router.dispatch(s)
            key = "ok"
        except Overloaded as e:
            from paddle_tpu.serving import Unavailable
            key = "failed" if isinstance(e, Unavailable) else "shed"
        except ServingError:
            key = "failed"
        with lock:
            counts[key] += 1

    # ---- the ramp: closed-loop saturation ---------------------------
    # single-dispatch rate understates capacity (the batcher coalesces
    # max_batch rows per launch), so pace-to-a-rate can sit inside
    # batched capacity on a fast host and never queue. A CLOSED loop
    # of many concurrent callers queues by construction —
    # host-drift-proof saturation, the same discipline as best-of-R.
    stop_load = threading.Event()
    pool = [mk_sample() for _ in range(32)]

    def worker(w):
        i = w
        while not stop_load.is_set():
            one(pool[i % len(pool)])
            i += 1

    ramp_s = float(os.environ.get("BENCH_AUTOSCALE_RAMP_S", "5.0"))
    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(64)]
    for th in threads:
        th.start()
    # thresholds SELF-CALIBRATE against the loaded signal: the first
    # second of the ramp (autoscaler not yet running) samples the
    # 1-replica backlog hint the policy will read; scale-up triggers at
    # half the typical loaded signal (2x crossing margin at any host
    # speed), scale-down just above the engine's IDLE floor (its
    # batch_timeout) — absolute ms thresholds would be host-drift bait
    samples = []
    cal_deadline = time.monotonic() + 1.0
    while time.monotonic() < cal_deadline:
        b = router.load_backlog_ms()
        if b is not None:
            samples.append(b)
        time.sleep(0.025)
    samples.sort()
    sig = (samples[len(samples) // 2] if samples
           and samples[len(samples) // 2] > 0
           else (samples[-1] if samples else 10.0))
    down_ms = max(1.6 * 2.0, 0.15 * sig)
    up_ms = max(2.2 * down_ms, 0.5 * sig)
    scaler = Autoscaler(
        fleet, min_replicas=1, max_replicas=max_replicas,
        up_backlog_ms=up_ms, down_backlog_ms=down_ms,
        sustain_up_s=0.2, sustain_down_s=1.0, cooldown_s=0.5,
        poll_ms=50.0).start()
    ramp_deadline = time.monotonic() + ramp_s
    while time.monotonic() < ramp_deadline:
        time.sleep(0.05)
    stop_load.set()
    for th in threads:
        th.join(120.0)
    ramp_snap = router.metrics.snapshot()
    peak = max(n for _, n in scaler.trajectory)
    # ---- sustained idle: the fleet must come back to the floor ------
    idle_deadline = time.monotonic() + 30.0
    while (fleet.replica_count() > 1
           and time.monotonic() < idle_deadline):
        time.sleep(0.1)
    scaler.stop()
    final = fleet.replica_count()
    traj = [n for _, n in scaler.trajectory]
    snap = router.metrics.snapshot()
    res = {
        "autoscale_closed_loop_callers": 64,
        "autoscale_ramp_s": ramp_s,
        "autoscale_base_ms": round(base_ms, 2),
        "autoscale_loaded_signal_ms": round(sig, 2),
        "autoscale_up_backlog_ms": round(up_ms, 2),
        "autoscale_down_backlog_ms": round(down_ms, 2),
        "autoscale_replica_trajectory": traj,
        "autoscale_trajectory_t_s": [t for t, _ in scaler.trajectory],
        "autoscale_peak_replicas": peak,
        "autoscale_final_replicas": final,
        "autoscale_min_replicas": 1,
        "autoscale_max_replicas": max_replicas,
        "autoscale_p99_ms": ramp_snap["fleet_latency_ms"]["p99_ms"],
        "autoscale_p50_ms": ramp_snap["fleet_latency_ms"]["p50_ms"],
        "autoscale_ok": counts["ok"],
        "autoscale_shed": counts["shed"],
        "autoscale_failed_non_shed": counts["failed"],
        "autoscale_scale_up_total": snap["scale_up_total"],
        "autoscale_scale_down_total": snap["scale_down_total"],
        "scaleup_cold_trace_ms": round(scaleup_cold_ms, 1),
        "scaleup_warm_cache_ms": (round(min(scaleup_ms), 1)
                                  if scaleup_ms else None),
    }
    # the acceptance invariants, asserted where the evidence is made
    assert counts["failed"] == 0, res
    assert peak > 1, ("the ramp never scaled up", res)
    assert all(1 <= n <= max_replicas for n in traj), res
    assert final == 1, ("idle never scaled back down", res)
    p99 = res["autoscale_p99_ms"]
    assert p99 is not None and p99 < 1e3 * 60, res  # bounded, not hung
    router.shutdown(drain=False)
    return res


def bench_router_failover():
    """Router-kill failover time (``--fleet`` → BENCH_r14.json): two
    role-fenced routers (active + warm standby) front two replicas;
    open-loop traffic rides HA client endpoints; a seeded chaos
    partition silences the active's lease renewals and the harness
    tears its listener down at the seeded moment (the router-process
    kill). Reported: kill → standby-adoption lag and kill → first
    standby-answered OK (both must land within the lease ttl plus a
    few health intervals — asserted), with zero failed non-shed
    requests (asserted)."""
    import tempfile
    import threading

    import numpy as np
    import jax
    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network
    from paddle_tpu.data import dense_vector, integer_value
    from paddle_tpu.dist.master import InMemStore, RoleLease
    from paddle_tpu.serving import (EngineTransport, Overloaded,
                                    ReplicaRouter, RouterHA,
                                    ServingClient, ServingEngine,
                                    ServingError, ServingPredictor,
                                    Unavailable, make_router_server)
    from paddle_tpu.testing import chaos

    dim, classes = 8, 4
    dsl.reset()
    x = dsl.data(name="x", size=dim)
    lab = dsl.data(name="label", size=classes)
    out = dsl.fc(input=x, size=classes, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    feeding = {"x": dense_vector(dim), "label": integer_value(classes)}
    cache_dir = tempfile.mkdtemp(prefix="paddle_tpu_aot_ha_")

    def build_engine():
        pred = ServingPredictor(graph, params, ["out"], feeding,
                                batch_buckets=[1, 2],
                                aot_cache=cache_dir)
        return ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                             queue_depth=64).start(warmup=True)

    sample = ((np.arange(dim, dtype=float) / dim).tolist(), 1)
    ttl, interval_ms = 0.4, 100.0
    engs = [build_engine() for _ in range(2)]
    store = InMemStore()
    lease_a = RoleLease(store, "A", ttl_s=ttl, settle_s=0.0)
    lease_b = RoleLease(store, "B", ttl_s=ttl, settle_s=0.0)
    active = ReplicaRouter([EngineTransport(e) for e in engs],
                           fence=lease_a, health_poll_ms=25.0)
    standby = ReplicaRouter([], fence=lease_b, health_poll_ms=25.0)
    srv_a = make_router_server(active, port=0)
    srv_b = make_router_server(standby, port=0)
    for s in (srv_a, srv_b):
        threading.Thread(target=s.serve_forever, daemon=True).start()
    by_id = {f"r{i}": e for i, e in enumerate(engs)}

    def peer_healthz():
        import http.client
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv_a.server_address[1], timeout=1.0)
        try:
            conn.request("GET", "/healthz")
            return json.loads(conn.getresponse().read())
        finally:
            conn.close()

    def adopt(snaps):
        return [(s["id"], EngineTransport(by_id[s["id"]]))
                for s in snaps if s["id"] in by_id]

    assert lease_a.try_acquire()
    active.start()
    standby.start()
    ha_a = RouterHA(active, lease_a, interval_ms=interval_ms).start()
    ha_b = RouterHA(standby, lease_b, peer_healthz=peer_healthz,
                    adopt=adopt, adopt_after=2,
                    interval_ms=interval_ms).start()
    plan = chaos.FaultPlan(seed=17, faults=[
        # drop holder A's renewals only — the adopted standby's own
        # renewals must sail through (chaos "match" targeting)
        {"type": "partition", "site": "lease_renew", "after": 4,
         "count": 100000, "match": {"holder": "A"}}])
    n_requests, req_interval = 40, 0.05
    counts = {"ok": 0, "shed": 0, "failed": 0}
    lock = threading.Lock()
    endpoints = [f"127.0.0.1:{srv_a.server_address[1]}",
                 f"127.0.0.1:{srv_b.server_address[1]}"]
    killed = {"t": None}
    first_standby_ok = {"t": None}

    def kill_watch():
        while plan.hits("lease_renew") < 5:
            time.sleep(0.01)
        killed["t"] = time.monotonic()
        # the active router "process" dies: stop the accept loop AND
        # close the listening socket (a real death frees the port;
        # shutdown() alone would backlog-blackhole new connections)
        srv_a.shutdown()
        srv_a.server_close()

    def one(i):
        client = ServingClient(endpoints=list(endpoints), timeout=10.0,
                               retries=8, backoff_base_ms=20.0,
                               backoff_seed=1000 + i)
        try:
            client.score(sample)
            key = "ok"
            # EXACT endpoint compare: a suffix match on the port digits
            # could credit the ACTIVE (e.g. :18080 ends with "8080")
            ep = (client.last_provenance or {}).get("endpoint", "")
            if ep == f"127.0.0.1:{srv_b.server_address[1]}":
                with lock:
                    if first_standby_ok["t"] is None:
                        first_standby_ok["t"] = time.monotonic()
        except Unavailable:
            key = "failed"
        except Overloaded:
            key = "shed"
        except (ServingError, OSError):
            key = "failed"
        with lock:
            counts[key] += 1

    threads = []
    with chaos.chaos_plan(plan):
        watcher = threading.Thread(target=kill_watch, daemon=True)
        watcher.start()
        t0 = time.monotonic()
        for i in range(n_requests):
            target = t0 + i * req_interval
            d = target - time.monotonic()
            if d > 0:
                time.sleep(d)
            th = threading.Thread(target=one, args=(i,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(60.0)
        watcher.join(10.0)
        deadline = time.monotonic() + 10.0
        while ha_b.adoptions == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
    assert killed["t"] is not None and ha_b.adoptions == 1
    adoption_lag_ms = 1e3 * (ha_b.adopted_at - killed["t"])
    answer_lag_ms = (1e3 * (first_standby_ok["t"] - killed["t"])
                     if first_standby_ok["t"] is not None else None)
    res = {
        "failover_requests": n_requests,
        "failover_ok": counts["ok"],
        "failover_shed": counts["shed"],
        "fleet_failed_non_shed_failover": counts["failed"],
        "failover_adoption_lag_ms": round(adoption_lag_ms, 1),
        "failover_kill_to_first_standby_ok_ms": (
            round(answer_lag_ms, 1) if answer_lag_ms else None),
        "failover_lease_ttl_ms": ttl * 1e3,
        "failover_health_interval_ms": interval_ms,
        "failover_adoptions": ha_b.adoptions,
        "failover_fenced_total": (
            active.metrics.snapshot()["fenced_total"]),
    }
    # acceptance: zero failed non-shed, and the standby ANSWERED within
    # one health interval of becoming eligible (lease ttl after the
    # kill), with scheduling slack for the 1-core host
    assert counts["failed"] == 0, res
    budget_ms = ttl * 1e3 + 3 * interval_ms + 500.0
    assert adoption_lag_ms < budget_ms, res
    assert answer_lag_ms is not None and answer_lag_ms < budget_ms + \
        500.0, res
    ha_a.shutdown(release=False)
    ha_b.shutdown(release=False)
    srv_b.shutdown()
    for e in engs:
        e.shutdown(drain=False)
    return res


def bench_fleet_trace(rounds=None, n_requests=None):
    """Tracing pays for itself (``--fleet`` → BENCH_r15.json +
    TRACE_r15.json): two replicas behind the router HTTP frontend,
    scored sequentially with tracing OFF and ON in interleaved
    best-of-R rounds (CLAUDE.md host-drift rule: a single A/B pair is
    meaningless on this box — each mode keeps its best p50). Reported:
    p50 per mode, the on-vs-off overhead in percent (asserted ≤ 5%, the
    docs/observability.md policy bound), and the acceptance trace — one
    scored request with an induced failover whose spans reconstruct the
    client-observed latency (root ``client.request`` wall time within
    5% of the measured call) with the failover visible as sibling
    ``router.attempt`` spans; the trace dumps to ``TRACE_r15.json``
    and must pass its own PT401 schema before this function returns."""
    import statistics
    import tempfile
    import threading

    import numpy as np
    import jax
    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network
    from paddle_tpu.data import dense_vector, integer_value
    from paddle_tpu.obs import trace as _trace
    from paddle_tpu.serving import (EngineTransport, ReplicaRouter,
                                    ServingClient, ServingEngine,
                                    ServingPredictor,
                                    make_router_server)
    from paddle_tpu.testing import chaos

    rounds = int(os.environ.get("BENCH_TRACE_ROUNDS", "3")
                 if rounds is None else rounds)
    n_requests = int(os.environ.get("BENCH_TRACE_REQUESTS", "30")
                     if n_requests is None else n_requests)
    dim, classes = 8, 4
    dsl.reset()
    x = dsl.data(name="x", size=dim)
    lab = dsl.data(name="label", size=classes)
    out = dsl.fc(input=x, size=classes, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    feeding = {"x": dense_vector(dim), "label": integer_value(classes)}
    cache_dir = tempfile.mkdtemp(prefix="paddle_tpu_aot_trace_")

    def build_engine():
        pred = ServingPredictor(graph, params, ["out"], feeding,
                                batch_buckets=[1, 2],
                                aot_cache=cache_dir)
        return ServingEngine(pred, max_batch=2, batch_timeout_ms=1.0,
                             queue_depth=64).start(warmup=True)

    engines = [build_engine() for _ in range(2)]
    router = ReplicaRouter([EngineTransport(e) for e in engines],
                           health_poll_ms=25.0).start()
    server = make_router_server(router, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    client = ServingClient(port=server.server_address[1])
    sample = ((np.arange(dim, dtype=float) / dim).tolist(), 1)
    client.score(sample)  # connection path + menu warm before timing

    # ---- the A/B: interleaved PER REQUEST (host throughput drifts
    # ±50% between windows — alternating modes request by request puts
    # both arms under the same drift, and best-of-R rounds on top
    # absorbs what alternation cannot), each mode keeps its best p50
    ab_tracer = _trace.Tracer("bench", buffer=65536)

    def p50_pair():
        lat = {"off": [], "on": []}
        for i in range(2 * n_requests):
            mode = "on" if i % 2 else "off"
            _trace.install(ab_tracer if mode == "on" else None)
            t0 = time.perf_counter()
            client.score(sample)
            lat[mode].append(1e3 * (time.perf_counter() - t0))
        _trace.install(None)
        return (statistics.median(lat["off"]),
                statistics.median(lat["on"]))

    best = {"off": float("inf"), "on": float("inf")}
    try:
        for _ in range(rounds):
            off, on = p50_pair()
            best["off"] = min(best["off"], off)
            best["on"] = min(best["on"], on)
        overhead_pct = 1e2 * (best["on"] - best["off"]) / best["off"]

        # ---- the acceptance trace: one scored request, induced
        # failover, spans reconstruct the client measurement ----------
        tracer = _trace.install(_trace.Tracer("bench"))
        plan = chaos.FaultPlan(seed=15, faults=[
            {"type": "drop", "site": "route_dispatch", "at": 1},
            {"type": "delay", "site": "serve_batch", "at": 1,
             "seconds": 0.05}])
        with chaos.chaos_plan(plan):
            t0 = time.perf_counter()
            result = client.score(sample)
            measured_ms = 1e3 * (time.perf_counter() - t0)
        prov = result["provenance"]
        tid = prov["trace_id"]
        # the worker emits replica.score THEN its phase children; wait
        # for phase.decode (the last write of that sequence) so the
        # committed artifact always carries the full phase split
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            spans = tracer.spans(tid)
            if any(s["name"] == "phase.decode" for s in spans):
                break
            time.sleep(0.01)
        else:
            raise RuntimeError(
                "acceptance trace never grew its phase.decode span — "
                "refusing to commit an incomplete TRACE artifact "
                f"(got {sorted(s['name'] for s in spans)})")
        attempts = [s for s in spans if s["name"] == "router.attempt"]
        roots = [s for s in spans if s["name"] == "client.request"]
        root_ms = roots[0]["dur_ms"] if roots else None
    finally:
        _trace.install(None)
        server.shutdown()
        server.server_close()  # free the listening socket, not just
        # the accept loop — shutdown() alone backlog-blackholes
        router.shutdown(drain=False)

    here = os.path.dirname(os.path.abspath(__file__))
    trace_path = os.path.join(here, "TRACE_r15.json")
    with open(trace_path, "w") as f:
        json.dump({"metric": "failover_trace", "trace_id": tid,
                   "client_measured_ms": round(measured_ms, 3),
                   "spans": spans}, f, indent=1)
    from paddle_tpu.analysis.bench_schema import check_bench_file
    schema_findings = check_bench_file(trace_path, "TRACE_r15.json")
    res = {
        "trace_rounds": rounds,
        "trace_requests_per_round": n_requests,
        "trace_off_p50_ms": round(best["off"], 3),
        "trace_on_p50_ms": round(best["on"], 3),
        "trace_overhead_pct": round(overhead_pct, 2),
        "trace_failovers": prov["failovers"],
        "trace_attempt_spans": len(attempts),
        "trace_span_count": len(spans),
        "trace_client_measured_ms": round(measured_ms, 3),
        "trace_root_span_ms": (round(root_ms, 3)
                               if root_ms is not None else None),
        "trace_root_delta_pct": (
            round(1e2 * abs(measured_ms - root_ms) / measured_ms, 2)
            if root_ms is not None else None),
        "trace_schema_findings": len(schema_findings),
    }
    # acceptance, asserted where the evidence is made: the failover is
    # two sibling attempts of ONE trace, the root span reconstructs the
    # client measurement within 5%, the artifact passes its schema, and
    # tracing costs ≤ 5% on the interleaved best-of p50 (honest about
    # drift: both arms already kept their best round)
    assert prov["failovers"] == 1 and len(attempts) == 2, res
    assert len({a["parent_id"] for a in attempts}) == 1, res
    assert root_ms is not None \
        and abs(measured_ms - root_ms) <= 0.05 * measured_ms, res
    assert schema_findings == [], [f.message for f in schema_findings]
    assert overhead_pct <= 5.0, res
    return res


def bench_serve_train(requests=None, batch_rows=None):
    """The r20 online loop end to end (``--serve_train`` →
    BENCH_r20.json): one process group closes
    serving→training→publish→serving.

    1. **The live loop.** A 2-replica fleet serves a published PTM1 CTR
       artifact; an open-loop traffic driver scores labeled rows
       through the router while the MAIN thread trains the replay
       stream the engines append (sealed PTRL1 segments → ledger tasks
       → sparse-lazy Momentum batches). On the publish cadence the
       trainer's weights merge + roll across the fleet pinned to the
       artifact digest. Evidence: held-out CTR error FALLS across the
       published versions (each artifact re-scored through the serving
       predictor — the model the fleet actually answered with), zero
       failed non-shed requests through every reload, zero hot-path
       recompiles (every engine's hardened guards stay silent).
    2. **Chaos drills**, trainer-only (the matrix cells' shapes at
       bench scale): a seeded kill mid-loop + rebuilt-loop resume that
       must be BITWISE the never-killed twin (exactly-once), and a
       NaN-poisoned batch the divergence sentry must skip with every
       published artifact staying finite (zero bad publishes).
    """
    import shutil
    import tempfile
    import threading

    import numpy as np
    import jax
    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.dist.checkpoint import Checkpointer
    from paddle_tpu.models import ctr_model
    from paddle_tpu.online import (ModelPublisher, ReplayTailer,
                                   ReplayWriter, ServeTrainLoop)
    from paddle_tpu.optim import Momentum
    from paddle_tpu.serving import (EngineTransport, Overloaded,
                                    ReplicaRouter, ServingEngine,
                                    ServingError, ServingPredictor)
    from paddle_tpu.testing import chaos
    from paddle_tpu.trainer import SGD
    from paddle_tpu.trainer.merge_model import load_merged_ex

    requests = int(os.environ.get("BENCH_SERVE_TRAIN_REQUESTS", "200")
                   if requests is None else requests)
    batch_rows = int(batch_rows or 10)
    vocab, maxlen, marker = 50, 16, 2
    seg_records, publish_every = 20, 6
    feeding = {"words": integer_value_sequence(vocab),
               "label": integer_value(2)}

    def build_trainer(seed=0):
        dsl.reset()
        cost, _out, _names = ctr_model(vocab_size=vocab, embed_dim=16,
                                       hidden=32, classes=2)
        tr = SGD(cost=cost,
                 update_equation=Momentum(learning_rate=0.1, momentum=0.9),
                 seed=seed)
        # the sparse-lazy path IS the subject: touched-rows slots only
        assert "t_rows" in tr.opt_state["slots"]["_embed.w0"]
        return tr

    def mk_rows(n, seed):
        # learnable CTR traffic: label = presence of the marker token
        # (positives carry it in ~1/3 of positions). Rows keep their
        # label slot — the feedback join the replay log trains on.
        rng = np.random.RandomState(seed)
        rows = []
        for _ in range(n):
            length = int(rng.randint(5, maxlen + 1))
            ids = rng.randint(3, vocab, size=length)
            label = int(rng.rand() < 0.5)
            if label:
                k = max(1, length // 3)
                ids[rng.choice(length, size=k, replace=False)] = marker
            rows.append(([int(i) for i in ids], label))
        return rows

    held = mk_rows(100, seed=99)
    work = tempfile.mkdtemp(prefix="paddle_tpu_serve_train_")
    replay_dir = os.path.join(work, "replay")
    publish_dir = os.path.join(work, "published")
    cache_dir = os.path.join(work, "aot")

    # ---- phase 1: the live loop ------------------------------------
    trainer = build_trainer()
    writer = ReplayWriter(replay_dir, segment_records=seg_records,
                          schema=list(feeding))
    engines_made = []

    def make_engine(model_path):
        pred = ServingPredictor.from_merged(
            model_path, feeding, batch_buckets=[1, 4],
            length_buckets=[maxlen], aot_cache=cache_dir)
        eng = ServingEngine(pred, max_batch=4, batch_timeout_ms=2.0,
                            queue_depth=requests + 8,
                            replay_sink=writer).start(warmup=True)
        engines_made.append(eng)
        return eng

    publisher = ModelPublisher(
        trainer, model_dir=publish_dir, outputs=["output"],
        build_transport=lambda path, rid: EngineTransport(
            make_engine(path)),
        every_batches=publish_every)
    publisher.publish()  # v0: the fleet's starting artifact
    router = ReplicaRouter(
        [EngineTransport(make_engine(publisher.last_good))
         for _ in range(2)],
        spawn=lambda rid: EngineTransport(
            make_engine(publisher.last_good)),
        health_poll_ms=25.0).start()
    publisher.router = router

    tailer = ReplayTailer(replay_dir, batch_rows=batch_rows,
                          scan_period_s=0.1, poll_s=0.02)
    loop = ServeTrainLoop(
        trainer, tailer=tailer, publisher=publisher,
        feeder=DataFeeder(feeding, pad_multiple=maxlen), writer=writer,
        health={"sentry": True, "policy": "skip_batch"})

    samples = mk_rows(requests, seed=7)
    counts = {"ok": 0, "shed": 0, "failed": 0}
    clock = threading.Lock()
    # calibrate the open-loop rate off sequential dispatches, then
    # offer ~1.5x so queues form without drowning the shared core
    t0 = time.perf_counter()
    for s in samples[:8]:
        router.dispatch(s)
    interval = (time.perf_counter() - t0) / 8 / 1.5

    def one(s):
        from paddle_tpu.serving import Unavailable
        try:
            router.dispatch(s)
            key = "ok"
        except Unavailable:
            key = "failed"  # no ready replica = outage, not backpressure
        except Overloaded:
            key = "shed"
        except ServingError:
            key = "failed"
        with clock:
            counts[key] += 1

    def drive():
        threads, t_start = [], time.perf_counter()
        for i, s in enumerate(samples[8:]):
            target = t_start + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            th = threading.Thread(target=one, args=(s,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(300.0)
        loop.stop()  # seal the tail, close the stream: the reader drains

    driver = threading.Thread(target=drive, name="traffic-driver")
    driver.start()
    loop.run()  # the MAIN thread trains the stream, publishing on cadence
    driver.join(300.0)
    router.shutdown(drain=True)

    # held-out error of every published version, re-scored through the
    # serving predictor — the artifact the fleet answered with, not the
    # trainer's live params
    def artifact_error(path):
        pred = ServingPredictor.from_merged(
            path, feeding, batch_buckets=[20], length_buckets=[maxlen])
        wrong = 0
        for i in range(0, len(held), 20):
            outs, _info = pred.predict_rows(held[i:i + 20])
            pick = np.argmax(outs["output"], axis=1)
            wrong += sum(int(p) != r[1]
                         for p, r in zip(pick, held[i:i + 20]))
        return wrong / len(held)

    artifacts = sorted(os.path.join(publish_dir, p)
                       for p in os.listdir(publish_dir)
                       if p.endswith(".ptmodel"))
    trajectory = [round(artifact_error(p), 4) for p in artifacts]

    # zero hot-path recompiles: every engine ever built (initial fleet +
    # each reload wave) kept its hardened guards silent and its worker
    # alive; check_guards() would raise on any post-warmup cache growth
    for eng in engines_made:
        assert eng.fatal is None, repr(eng.fatal)
        eng.predictor.check_guards()
        eng.shutdown()

    res = {
        "serve_train_requests": requests,
        "serve_train_open_loop_interval_ms": round(interval * 1e3, 3),
        "serve_train_ok": counts["ok"] + 8,  # calibration answered too
        "serve_train_shed": counts["shed"],
        "fleet_failed_non_shed": counts["failed"],
        "serve_train_batches_trained": loop.batches_trained,
        "serve_train_replay_segments": writer.segments_sealed,
        "serve_train_replay_rows": writer.records_total,
        "publishes_total": publisher.publishes_total,
        "rollbacks_total": publisher.rollbacks_total,
        "serve_train_error_trajectory": trajectory,
        "serve_train_hot_path_recompiles": 0,  # asserted above
        "serve_train_engines_built": len(engines_made),
    }
    # acceptance, asserted where the evidence is made: the loop LEARNED
    # the traffic across ≥2 published versions, and every reload wave
    # swapped under load without failing a single non-shed request
    assert len(trajectory) >= 2 and trajectory[-1] < trajectory[0], res
    assert counts["failed"] == 0, res
    assert publisher.publishes_total >= 2, res

    # ---- phase 2: chaos drills (trainer-only, matrix shapes) -------
    def final_state(tr):
        from paddle_tpu.trainer.checkpoint import _flatten
        params = {k: np.asarray(jax.device_get(v))
                  for k, v in tr._params_for_save().items()}
        return params, _flatten(tr._opt_state_for_save()), \
            np.asarray(jax.device_get(tr._rng))

    def drill_loop(rdir, mdir, *, ck_dir=None, health=None):
        tr = build_trainer()
        t = ReplayTailer(rdir, batch_rows=batch_rows, poll_s=0.01)
        pub = ModelPublisher(tr, model_dir=mdir, outputs=["output"],
                             every_batches=3)
        ck = None
        if ck_dir is not None:
            ck = Checkpointer(ck_dir, saving_period=1,
                              saving_period_by_batches=2, background=True)
        lp = ServeTrainLoop(tr, tailer=t, publisher=pub,
                            feeder=DataFeeder(feeding,
                                              pad_multiple=maxlen),
                            checkpointer=ck, health=health)
        t.end_stream()  # drain mode: traffic pre-sealed below
        return lp, tr, pub, ck

    drill_rows = mk_rows(60, seed=21)
    kill_dir = os.path.join(work, "drill_kill")
    twin_dir = os.path.join(work, "drill_twin")
    w = ReplayWriter(kill_dir, segment_records=seg_records)
    for r in drill_rows:
        w.append(r)
    w.close()
    shutil.copytree(kill_dir, twin_dir)  # BEFORE any ledger exists

    lp, tr, _, _ = drill_loop(twin_dir, os.path.join(work, "m_twin"),
                              ck_dir=os.path.join(work, "ck_twin"))
    lp.run()
    want = final_state(tr)

    plan = chaos.FaultPlan(seed=0, faults=[
        {"type": "kill", "site": "step_done", "at": 4, "mode": "raise"}])
    lp, tr, _, ck = drill_loop(kill_dir, os.path.join(work, "m_kill"),
                               ck_dir=os.path.join(work, "ck_kill"))
    with chaos.chaos_plan(plan):
        try:
            lp.run()
            raise AssertionError("chaos kill never fired")
        except chaos.ChaosKilled:
            pass
    ck.flush()
    lp, tr, _, _ = drill_loop(kill_dir, os.path.join(work, "m_kill"),
                              ck_dir=os.path.join(work, "ck_kill"))
    lp.run()
    got = final_state(tr)
    for g, wv in ((got[0], want[0]), (got[1], want[1])):
        assert set(g) == set(wv)
        for k in wv:
            np.testing.assert_array_equal(g[k], wv[k], err_msg=k)
    np.testing.assert_array_equal(got[2], want[2])
    res["serve_train_resume_exactly_once_bitwise"] = True

    poison_dir = os.path.join(work, "drill_poison")
    w = ReplayWriter(poison_dir, segment_records=seg_records)
    for r in drill_rows:
        w.append(r)
    w.close()
    plan = chaos.FaultPlan(seed=0, faults=[
        {"type": "corrupt", "site": "step_stats", "at": 3}])
    lp, tr, pub, _ = drill_loop(
        poison_dir, os.path.join(work, "m_poison"),
        health={"period": 1, "sentry": True, "policy": "skip_batch"})
    with chaos.chaos_plan(plan):
        lp.run()
    snap = tr._health.snapshot()
    bad = 0
    for p in os.listdir(os.path.join(work, "m_poison")):
        _, params, _, _ = load_merged_ex(
            os.path.join(work, "m_poison", p))
        bad += any(not np.isfinite(v).all() for v in params.values())
    # the sentry skipped the poisoned update; nothing poisoned published
    assert snap["sentry_trips"] == 1 and snap["skipped_batches"] == 1, snap
    assert pub.publishes_total >= 1 and bad == 0, (pub.publishes_total,
                                                   bad)
    res["serve_train_poison_sentry_trips"] = snap["sentry_trips"]
    res["serve_train_poison_bad_publishes"] = bad
    shutil.rmtree(work, ignore_errors=True)
    return res


def fleet_main():
    """``python bench.py --fleet``: the off-tunnel fleet benches alone,
    forced onto CPU; one JSON line, mirrored to BENCH_r15.json. Four
    scenarios in one artifact: the r13 cold-start A/B + replica-kill
    rounds (still the respawn-warmth evidence), the autoscale traffic
    ramp (replica count follows load inside [min, max], p99 bounded,
    zero failed non-shed), the router-kill HA failover (standby answers
    within one health interval, zero failed non-shed), and the r15
    tracing A/B (on-vs-off p50 overhead ≤ 5%, failover trace →
    TRACE_r15.json)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "serving_fleet_autoscale_ha_failover",
              "platform": jax.devices()[0].platform}
    result.update(bench_fleet())
    result.update(bench_fleet_autoscale())
    result.update(bench_router_failover())
    result.update(bench_fleet_trace())
    # the headline zero-drop number sums EVERY scenario's counter —
    # no failure hides behind a sibling scenario
    result["fleet_failed_non_shed"] = (
        result["fleet_failed_non_shed"]
        + result["autoscale_failed_non_shed"]
        + result["fleet_failed_non_shed_failover"])
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r15.json"), "w") as f:
        f.write(line + "\n")
    return 0


def decode_main():
    """``python bench.py --decode``: the off-tunnel decode A/B alone,
    forced onto CPU; one JSON line, mirrored to BENCH_r10.json."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "decode_early_exit_continuous_batching_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_decode())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r10.json"), "w") as f:
        f.write(line + "\n")
    return 0


def serving_main():
    """``python bench.py --serving``: the off-tunnel serving A/B alone,
    forced onto CPU; one JSON line, mirrored to BENCH_r09.json."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "serving_dynamic_batching_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_serving())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r09.json"), "w") as f:
        f.write(line + "\n")
    return 0


def quant_main():
    """``python bench.py --quant``: the off-tunnel quantized-serving
    three-way alone, forced onto CPU; one JSON line, mirrored to
    BENCH_r19.json."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "serving_quant_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_serving_quant())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r19.json"), "w") as f:
        f.write(line + "\n")
    return 0


def serve_train_main():
    """``python bench.py --serve_train``: the off-tunnel online-loop
    evidence alone, forced onto CPU; one JSON line, mirrored to
    BENCH_r20.json."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "serve_train_loop",
              "platform": jax.devices()[0].platform}
    result.update(bench_serve_train())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r20.json"), "w") as f:
        f.write(line + "\n")
    return 0


def autotune_main():
    """``python bench.py --autotune``: the off-tunnel self-tuning A/B
    alone, forced onto CPU; one JSON line, mirrored to BENCH_r21.json,
    with the two recorded traces committed as WORKLOAD_r21_*.json (the
    PT401 ``WORKLOAD_*`` family — ``tests/test_workload_replay.py``
    replays them)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "serving_autotune_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_autotune())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r21.json"), "w") as f:
        f.write(line + "\n")
    return 0


def pipeline_main():
    """``python bench.py --pipeline``: the off-tunnel pipeline A/B alone,
    forced onto an 8-virtual-device CPU mesh; one JSON line, mirrored to
    BENCH_r08.json."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "pipeline_parallel_train_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_pipeline())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r08.json"), "w") as f:
        f.write(line + "\n")
    return 0


def zero1_main():
    """``python bench.py --zero1``: the off-tunnel ZeRO-1 A/B alone,
    forced onto an 8-virtual-device CPU mesh (no tunnel involvement);
    one JSON line, mirrored to BENCH_r07.json."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "zero1_sharded_optimizer_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_zero1())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r07.json"), "w") as f:
        f.write(line + "\n")
    return 0


def fsdp_main():
    """``python bench.py --fsdp``: the off-tunnel full-FSDP A/B alone,
    forced onto an 8-virtual-device CPU mesh (no tunnel involvement);
    one JSON line, mirrored to BENCH_r17.json."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "fsdp_full_param_sharding_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_fsdp())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r17.json"), "w") as f:
        f.write(line + "\n")
    return 0


def overlap_main():
    """``python bench.py --overlap``: the off-tunnel FSDP-overlap x
    fused-kernel 2x2 A/B alone, forced onto an 8-virtual-device CPU
    mesh (no tunnel involvement); one JSON line, mirrored to
    BENCH_r18.json."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "overlap_fsdp_fused_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_overlap())
    line = json.dumps(result)
    print(line, flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_r18.json"), "w") as f:
        f.write(line + "\n")
    return 0


def health_main():
    """``python bench.py --health``: the off-tunnel training-health A/B
    alone, forced onto CPU (no tunnel involvement); one JSON line,
    mirrored to BENCH_r16.json, with the armed run's sampled timeline
    committed as HEALTH_r16.json (the PT401 ``HEALTH_*`` family —
    ``tools/healthview.py`` renders/diffs it)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "training_health_telemetry_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_health())
    timeline = result.pop("_health_timeline")
    here = os.path.dirname(os.path.abspath(__file__))
    health_doc = {
        "run": "bench-r16-health",
        "platform": result["platform"],
        "period": result["health_period"],
        "sentry_trips": result["health_sentry_trips"],
        # the final measured pass's steps: a representative, bounded
        # sample of the per-step schema (full runs live in --health_log
        # JSONL files, not in git)
        "events": timeline[-result["health_batches"]:],
    }
    with open(os.path.join(here, "HEALTH_r16.json"), "w") as f:
        json.dump(health_doc, f, indent=1)
        f.write("\n")
    line = json.dumps(result)
    print(line, flush=True)
    with open(os.path.join(here, "BENCH_r16.json"), "w") as f:
        f.write(line + "\n")
    return 0


def input_pipeline_main():
    """``python bench.py --input-pipeline``: the off-tunnel metric alone,
    forced onto CPU (no tunnel involvement), one JSON line."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    result = {"metric": "input_pipeline_async_prefetch_ab",
              "platform": jax.devices()[0].platform}
    result.update(bench_input_pipeline())
    print(json.dumps(result), flush=True)
    return 0


def _watchdog(seconds, exit_code):
    """Force-exit the child after a deadline. A wedged tunnel hangs inside
    C calls where SIGALRM handlers never run, but a watchdog thread's
    os._exit always fires; already-flushed stdout survives."""
    import threading

    t = threading.Timer(seconds, lambda: os._exit(exit_code))
    t.daemon = True
    t.start()
    return t


def child_main():
    import jax
    result = {
        "metric": "lstm_imdb_train_ms_per_batch_bs64_h256_seq100",
        "value": None,
        "unit": "ms/batch",
        "vs_baseline": None,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }
    try:
        # which backend each baseline shape takes — pins perf claims to
        # dispatch (tests/test_ops_pallas.py::test_dispatch_table...)
        from paddle_tpu.ops.lstm import kernel_dispatch_table
        result["kernel_dispatch"] = kernel_dispatch_table()
    except Exception as e:  # noqa: BLE001
        result["kernel_dispatch"] = {"error": repr(e)[:120]}
    wd = _watchdog(1200, 7)  # nothing printed yet: die loudly, retry
    ms = bench_lstm()
    result["value"] = round(ms, 3)
    result["vs_baseline"] = round(REFERENCE_MS / ms, 3)
    # the primary metric is safe from here on: print it NOW so a wedge in
    # the extras can only cost the extras (the orchestrator takes the last
    # parseable line, and the extras watchdog exits 0)
    print(json.dumps(result), flush=True)
    wd.cancel()

    def extra(tag, fn):
        """Run one optional metric under a watchdog that can only cost the
        remaining extras. A pre-printed timeout marker ensures a watchdog
        os._exit leaves '<tag>_error: timeout' in the captured output
        rather than the metric silently vanishing."""
        result[f"{tag}_error"] = "timeout (watchdog, 420s)"
        print(json.dumps(result), flush=True)
        wd = _watchdog(420, 0)
        try:
            result.update(fn())
            del result[f"{tag}_error"]
        except Exception as e:  # noqa: BLE001
            result[f"{tag}_error"] = repr(e)[:300]
        wd.cancel()
        print(json.dumps(result), flush=True)

    extra("lstm_bf16", lambda: {"lstm_bf16_ms_per_batch": round(
        bench_lstm(compute_dtype="bfloat16"), 3)})
    extra("resnet50_bf16",
          lambda: bench_resnet50(compute_dtype="bfloat16",
                                 batch=int(os.environ.get(
                                     "BENCH_RESNET_BF16_BATCH", "256"))))
    extra("resnet50", bench_resnet50)
    extra("alexnet", lambda: bench_image_config("alexnet"))
    extra("googlenet", lambda: bench_image_config("googlenet"))
    extra("smallnet", lambda: bench_image_config("smallnet_mnist_cifar"))
    # the step-time-breakdown A/B rides along on-chip too, so a capture
    # window reports the same {steps/s, data_wait_frac} split off-tunnel
    # rounds record on CPU
    extra("input_pipeline", bench_input_pipeline)
    # ZeRO-1 sharded-optimizer A/B over the real device mesh (the
    # off-tunnel number lives in BENCH_r07.json via --zero1)
    extra("zero1", bench_zero1)
    # full-FSDP A/B (r17): param bytes/device ~1/N asserted, step-time
    # ratio recorded — on ICI the per-layer gathers overlap compute,
    # so the on-chip capture is where the ratio gets honest (off-tunnel
    # number: BENCH_r17.json via --fsdp)
    extra("fsdp", bench_fsdp)
    # FSDP gather-overlap x fused-kernel 2x2 (r18): on ICI the overlap
    # arm is where the exposed-comm shrink turns into step time, and
    # the fused arms take the real Pallas path — the on-chip capture
    # is the honest one (off-tunnel number: BENCH_r18.json via
    # --overlap)
    extra("overlap", bench_overlap)
    # pipeline-parallel A/B over the real mesh — on ICI the ppermute
    # hand-off overlaps compute, so this is where the schedule's win can
    # actually show (off-tunnel number: BENCH_r08.json via --pipeline)
    extra("pipeline", bench_pipeline)
    # serving A/B over the real chip: dynamic batching vs batch-size-1
    # (off-tunnel number: BENCH_r09.json via --serving)
    extra("serving", bench_serving)
    # quantized serving three-way (fp32/bf16/int8) with the warmup
    # accuracy gate asserted in-bench (off-tunnel: BENCH_r19.json via
    # --quant)
    extra("quant", bench_serving_quant)
    # decode A/B: early-exit chunked search vs full scan + continuous vs
    # convoy batching — armed here so the next tpu_watch.sh capture
    # window records on-chip decode numbers for free (off-tunnel number:
    # BENCH_r10.json via --decode)
    extra("decode", bench_decode)
    # fleet: AOT cold-start A/B + kill-and-respawn under load — on a
    # real chip the live-trace arm pays the tunnel's multi-minute XLA
    # compiles, which is exactly where the cache matters most
    # (off-tunnel number: BENCH_r14.json via --fleet)
    extra("fleet", bench_fleet)
    # self-operating fleet (r14): autoscale ramp + router-kill HA
    # failover — the control loops are host-agnostic, but on-chip the
    # scale-up arm shows the real cache-vs-trace gap
    extra("fleet_autoscale", bench_fleet_autoscale)
    extra("fleet_ha", bench_router_failover)
    # observability (r15): tracing on-vs-off p50 overhead through the
    # router + the failover trace artifact — on-chip the compute phase
    # dominates, so the off-tunnel CPU number is the overhead's honest
    # worst case (off-tunnel number: BENCH_r15.json via --fleet)
    extra("fleet_trace", bench_fleet_trace)
    # training-health plane (r16): stats-fused-into-the-step overhead
    # A/B + in-bench bitwise neutrality — rides the tpu_watch capture
    # so the on-chip overhead number comes for free (off-tunnel number:
    # BENCH_r16.json via --health; the timeline artifact stays CPU's)
    extra("health", lambda: {k: v for k, v in bench_health().items()
                             if not k.startswith("_")})
    # online loop (r20): serving traffic streamed into the sparse CTR
    # trainer with cadence hot-swap — the loop's control plane is
    # host-agnostic, so the on-chip window mostly dates the reload
    # waves; the off-tunnel number is BENCH_r20.json via --serve_train
    extra("serve_train", bench_serve_train)
    # self-tuning loop (r21): trace record -> grid tune -> defaults vs
    # tuned A/B + in-bench replay determinism — on-chip the absolute
    # latencies get honest while the structural ordering (shed counts)
    # stays host-agnostic; the off-tunnel number is BENCH_r21.json via
    # --autotune (which also refreshes the committed traces)
    extra("autotune", bench_autotune)
    return 0


def main():
    if "--input-pipeline" in sys.argv[1:]:
        return input_pipeline_main()
    if "--zero1" in sys.argv[1:]:
        return zero1_main()
    if "--fsdp" in sys.argv[1:]:
        return fsdp_main()
    if "--overlap" in sys.argv[1:]:
        return overlap_main()
    if "--pipeline" in sys.argv[1:]:
        return pipeline_main()
    if "--serving" in sys.argv[1:]:
        return serving_main()
    if "--quant" in sys.argv[1:]:
        return quant_main()
    if "--serve_train" in sys.argv[1:]:
        return serve_train_main()
    if "--autotune" in sys.argv[1:]:
        return autotune_main()
    if "--decode" in sys.argv[1:]:
        return decode_main()
    if "--fleet" in sys.argv[1:]:
        return fleet_main()
    if "--health" in sys.argv[1:]:
        return health_main()
    if os.environ.get("BENCH_CHILD") == "1":
        return child_main()

    def best_line(stdout):
        # the JSON line is the last stdout line that parses
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(parsed, dict) and parsed.get("value") is not None:
                return line
        return None

    last_tail = ""
    for attempt in range(RETRIES):
        env = dict(os.environ, BENCH_CHILD="1")
        # cheap probe first: when the tunnel is wedged even backend init
        # hangs, so don't spend a full bench timeout discovering that
        probe_ok, probe_msg = False, ""
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=150,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            probe_ok = probe.returncode == 0
            if not probe_ok:
                probe_msg = ("backend probe failed rc="
                             f"{probe.returncode}: "
                             + (probe.stderr or "")[-300:])
        except subprocess.TimeoutExpired:
            probe_msg = "backend probe hung (tunnel wedged?)"
        if not probe_ok:
            last_tail = probe_msg
        else:
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    capture_output=True, text=True, timeout=4200, env=env)
                stdout, stderr = proc.stdout, proc.stderr
            except subprocess.TimeoutExpired as e:
                # a killed child may still have printed the primary metric
                stdout = e.stdout.decode() if isinstance(e.stdout, bytes) \
                    else (e.stdout or "")
                stderr = "timeout after 4200s"
            line = best_line(stdout)
            if line is not None:
                print(line)
                return 0
            last_tail = ((stderr or "") + (stdout or ""))[-600:]
        if attempt < RETRIES - 1:
            wait = BACKOFFS[min(attempt, len(BACKOFFS) - 1)]
            print(f"# attempt {attempt + 1} failed; retrying in {wait}s",
                  file=sys.stderr)
            time.sleep(wait)
    # total failure: still emit a parseable JSON line, never a bare
    # traceback. If a mid-round live capture exists (tools/tpu_watch.sh
    # writes BENCH_LIVE_*.json the moment the tunnel answers), attach it —
    # clearly labeled as NOT measured by this run — so a wedged tunnel at
    # round end doesn't erase the round's real numbers.
    fail = {
        "metric": "lstm_imdb_train_ms_per_batch_bs64_h256_seq100",
        "value": None,
        "unit": "ms/batch",
        "vs_baseline": None,
        "error": last_tail,
        "attempts": RETRIES,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    live = [f for f in os.listdir(here)
            if f.startswith("BENCH_LIVE_") and f.endswith(".json")]
    if live:
        # newest by mtime, not name — r9 would sort after r10
        newest = max(live,
                     key=lambda f: os.path.getmtime(os.path.join(here, f)))
        try:
            with open(os.path.join(here, newest)) as f:
                fail["live_capture_not_this_run"] = {
                    "file": newest, "data": json.loads(f.read())}
        except (OSError, json.JSONDecodeError):
            pass
    print(json.dumps(fail))
    return 1


if __name__ == "__main__":
    sys.exit(main())
